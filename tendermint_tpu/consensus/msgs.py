"""Consensus wire messages (reference consensus/msgs.go;
proto/tendermint/consensus/types.proto Message oneof, fields 1-9).

``WireEncodeCache`` deduplicates ``encode_msg`` work across the reactor's
per-peer gossip routines: the same vote or block part is sent to every peer
and re-considered every loop iteration, but its wire bytes depend only on
content, so one encode serves all sends.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from ..libs import protowire as pw
from ..libs.bits import BitArray
from ..types.basic import BlockID, PartSetHeader, SignedMsgType
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote


@dataclass
class NewRoundStepMessage:
    height: int
    round: int
    step: int
    seconds_since_start_time: int
    last_commit_round: int


@dataclass
class NewValidBlockMessage:
    height: int
    round: int
    block_part_set_header: PartSetHeader
    block_parts: BitArray
    is_commit: bool


@dataclass
class ProposalMessageWire:
    proposal: Proposal


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray


@dataclass
class BlockPartMessageWire:
    height: int
    round: int
    part: Part


@dataclass
class VoteMessageWire:
    vote: Vote


@dataclass
class HasVoteMessage:
    height: int
    round: int
    type: SignedMsgType
    index: int


@dataclass
class VoteSetMaj23Message:
    height: int
    round: int
    type: SignedMsgType
    block_id: BlockID


@dataclass
class VoteSetBitsMessage:
    height: int
    round: int
    type: SignedMsgType
    block_id: BlockID
    votes: BitArray


def encode_msg(msg) -> bytes:
    """Message oneof envelope."""
    w = pw.Writer()
    if isinstance(msg, NewRoundStepMessage):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.varint(3, msg.step)
        b.varint(4, msg.seconds_since_start_time)
        b.varint(5, msg.last_commit_round)
        w.message(1, b.finish())
    elif isinstance(msg, NewValidBlockMessage):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.message(3, msg.block_part_set_header.encode())
        b.message_opt(4, msg.block_parts.encode() if msg.block_parts else None)
        b.bool(5, msg.is_commit)
        w.message(2, b.finish())
    elif isinstance(msg, ProposalMessageWire):
        b = pw.Writer()
        b.message(1, msg.proposal.encode())
        w.message(3, b.finish())
    elif isinstance(msg, ProposalPOLMessage):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.proposal_pol_round)
        b.message(3, msg.proposal_pol.encode())
        w.message(4, b.finish())
    elif isinstance(msg, BlockPartMessageWire):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.message(3, msg.part.encode())
        w.message(5, b.finish())
    elif isinstance(msg, VoteMessageWire):
        b = pw.Writer()
        b.message(1, msg.vote.encode())
        w.message(6, b.finish())
    elif isinstance(msg, HasVoteMessage):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.varint(3, int(msg.type))
        b.varint(4, msg.index)
        w.message(7, b.finish())
    elif isinstance(msg, VoteSetMaj23Message):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.varint(3, int(msg.type))
        b.message(4, msg.block_id.encode())
        w.message(8, b.finish())
    elif isinstance(msg, VoteSetBitsMessage):
        b = pw.Writer()
        b.varint(1, msg.height)
        b.varint(2, msg.round)
        b.varint(3, int(msg.type))
        b.message(4, msg.block_id.encode())
        b.message(5, msg.votes.encode())
        w.message(9, b.finish())
    else:
        raise ValueError(f"unknown consensus message {type(msg)}")
    return w.finish()


class WireEncodeCache:
    """Content-keyed cache of ``encode_msg`` outputs, shared across peers
    and gossip-loop iterations.

    Keys carry full message identity — (height, round, part-set-header
    hash, part index) for block parts, the signature for votes and
    proposals (a signature pins the exact signed content, so even
    equivocating votes at the same H/R/type/index key separately) — so a
    stale entry can never serve bytes for different content. Eviction is
    therefore pure memory management: LRU-bounded, plus the reactor
    explicitly prunes heights that fell out of the live gossip window on
    every height advance.
    """

    def __init__(self, max_entries: int = 1024):
        self.max_entries = max_entries
        self._entries: "OrderedDict[tuple, bytes]" = OrderedDict()
        self.stats = {"hits": 0, "misses": 0}
        self.metrics = None  # ConsensusMetrics, wired by the node

    def get(self, kind: str, height: int, key: Tuple,
            build: Callable[[], bytes]) -> bytes:
        k = (kind, height, key)
        buf = self._entries.get(k)
        m = self.metrics
        if buf is not None:
            self._entries.move_to_end(k)
            self.stats["hits"] += 1
            if m is not None:
                m.encode_cache_hits_total.labels(kind).inc()
            return buf
        buf = build()
        self.stats["misses"] += 1
        if m is not None:
            m.encode_cache_misses_total.labels(kind).inc()
        self._entries[k] = buf
        if len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
        return buf

    def vote(self, vote) -> bytes:
        return self.get(
            "vote", vote.height,
            (vote.round, int(vote.type), vote.validator_index, vote.signature),
            lambda: encode_msg(VoteMessageWire(vote)))

    def block_part(self, height: int, round_: int, psh_hash: bytes,
                   part) -> bytes:
        return self.get(
            "block_part", height, (round_, psh_hash, part.index),
            lambda: encode_msg(BlockPartMessageWire(height, round_, part)))

    def proposal(self, proposal) -> bytes:
        return self.get(
            "proposal", proposal.height, (proposal.round, proposal.signature),
            lambda: encode_msg(ProposalMessageWire(proposal)))

    def prune_below(self, height: int) -> int:
        """Drop entries below `height` (called on height advance; lagging
        catchup peers below the cutoff re-encode — LRU already bounds them)."""
        dead = [k for k in self._entries if k[1] < height]
        for k in dead:
            del self._entries[k]
        return len(dead)

    def __len__(self) -> int:
        return len(self._entries)


def decode_msg(data: bytes):
    fields = list(pw.iter_fields(data))
    if len(fields) != 1:
        raise ValueError("consensus Message must have exactly one oneof field")
    fn, _wt, body = fields[0]
    d = pw.fields_dict(body)

    def iv(n, default=0):
        vals = d.get(n)
        return pw.varint_to_int64(vals[0]) if vals else default

    def bv(n):
        vals = d.get(n)
        return vals[0] if vals else b""

    if fn == 1:
        return NewRoundStepMessage(iv(1), iv(2), iv(3), iv(4), iv(5))
    if fn == 2:
        return NewValidBlockMessage(
            iv(1), iv(2), PartSetHeader.decode(bv(3)),
            BitArray.decode(bv(4)) if d.get(4) else BitArray(0), bool(iv(5)))
    if fn == 3:
        return ProposalMessageWire(Proposal.decode(bv(1)))
    if fn == 4:
        return ProposalPOLMessage(iv(1), iv(2), BitArray.decode(bv(3)))
    if fn == 5:
        return BlockPartMessageWire(iv(1), iv(2), Part.decode(bv(3)))
    if fn == 6:
        return VoteMessageWire(Vote.decode(bv(1)))
    if fn == 7:
        return HasVoteMessage(iv(1), iv(2), SignedMsgType(iv(3)), iv(4))
    if fn == 8:
        return VoteSetMaj23Message(iv(1), iv(2), SignedMsgType(iv(3)),
                                   BlockID.decode(bv(4)))
    if fn == 9:
        return VoteSetBitsMessage(iv(1), iv(2), SignedMsgType(iv(3)),
                                  BlockID.decode(bv(4)), BitArray.decode(bv(5)))
    raise ValueError(f"unknown consensus Message field {fn}")
