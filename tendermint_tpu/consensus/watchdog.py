"""Consensus stall watchdog: injected fault → observable degradation.

The fault plane can blackhole a partition, open the device breaker, or
kill fsync — but a node that silently stops committing is still an
invisible failure unless something NOTICES. The watchdog samples the
committed height; when it hasn't advanced for ``stall_timeout_s`` the node

* increments ``consensus_stalled_total`` (the alertable signal),
* writes a debugdump bundle (thread/task stacks, round state, peer table,
  metrics snapshot — libs/debugdump.py) so the stall is diagnosable
  post-mortem even if the operator only looks hours later,
* logs CRITICAL with the stuck (height, round, step).

One dump per stall episode: the watchdog re-arms only after the height
moves again. Enabled via ``consensus.stall_watchdog_s`` (0 = off, the
default — a chain configured to idle between txs would false-positive).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

logger = logging.getLogger("tmtpu.watchdog")


class ConsensusWatchdog:
    def __init__(self, cs, stall_timeout_s: float,
                 metrics=None, dump_dir: Optional[str] = None,
                 dump_node=None, check_interval_s: Optional[float] = None,
                 height_fn=None):
        """``cs`` is the ConsensusState to observe; ``metrics`` a
        ConsensusMetrics (or None); ``dump_node`` whatever should be
        handed to debugdump.write_dump (a Node, or a shim with
        consensus_state/switch attributes, or None for stacks-only).
        ``height_fn`` overrides the progress probe — the node passes the
        block-store height, which advances during fast-sync too;
        ConsensusState.state only moves after switch_to_consensus, so
        sampling it alone would flag a >T-second block-sync as a stall."""
        self.cs = cs
        self._height_fn = height_fn
        self.stall_timeout_s = stall_timeout_s
        self.metrics = metrics
        self.dump_dir = dump_dir
        self.dump_node = dump_node
        self.check_interval_s = (check_interval_s if check_interval_s
                                 is not None
                                 else max(0.25, stall_timeout_s / 4))
        self.stalls = 0            # episodes observed (tests read this)
        self.last_dump_path: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self._last_height = -1
        self._last_advance_t = 0.0
        self._in_stall = False

    async def start(self) -> None:
        self._last_height = self._height()
        self._last_advance_t = time.monotonic()
        self._task = asyncio.create_task(self._run(),
                                         name=f"cs-watchdog-{id(self)}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _height(self) -> int:
        if self._height_fn is not None:
            return self._height_fn()
        return self.cs.state.last_block_height

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            h = self._height()
            now = time.monotonic()
            if h != self._last_height:
                self._last_height = h
                self._last_advance_t = now
                if self._in_stall:
                    logger.warning("consensus resumed at height %d after "
                                   "stall", h)
                    self._in_stall = False
                continue
            if (not self._in_stall
                    and now - self._last_advance_t >= self.stall_timeout_s):
                self._in_stall = True
                self.stalls += 1
                self._report(h, now - self._last_advance_t)

    def _report(self, height: int, idle_s: float) -> None:
        rs = getattr(self.cs, "rs", None)
        logger.critical(
            "consensus stalled: no commit for %.1fs (height=%d round=%s "
            "step=%s)", idle_s, height,
            getattr(rs, "round", "?"), getattr(rs, "step", "?"))
        if self.metrics is not None:
            self.metrics.consensus_stalled_total.inc()
        if self.dump_dir:
            try:
                from ..libs.debugdump import write_dump

                out = os.path.join(self.dump_dir,
                                   f"debug-stall-{int(time.time())}")
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                self.last_dump_path = write_dump(out, node=self.dump_node,
                                                 loop=loop)
                logger.critical("stall debugdump written to %s",
                                self.last_dump_path)
            except Exception:
                logger.exception("failed writing stall debugdump")
