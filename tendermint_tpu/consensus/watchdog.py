"""Consensus stall watchdog: injected fault → observable degradation.

The fault plane can blackhole a partition, open the device breaker, or
kill fsync — but a node that silently stops committing is still an
invisible failure unless something NOTICES. The watchdog samples the
committed height; when it hasn't advanced for ``stall_timeout_s`` the node

* increments ``consensus_stalled_total`` (the alertable signal),
* writes a debugdump bundle (thread/task stacks, round state, peer table,
  metrics snapshot — libs/debugdump.py) so the stall is diagnosable
  post-mortem even if the operator only looks hours later,
* logs CRITICAL with the stuck (height, round, step).

One dump per stall episode: the watchdog re-arms only after the height
moves again. Enabled via ``consensus.stall_watchdog_s`` (0 = off, the
default — a chain configured to idle between txs would false-positive).

Halt classification: not every stall is a mystery. During a quorum-loss
window (>1/3 of voting power isolated) a halt is the EXPECTED,
liveness-only consequence — Tendermint's safety argument requires it.
``classify_halt`` reads the current round's vote-set voting power and
per-validator vote bitmaps: when the power absent from the stage
blocking the round (the prevote set until it holds >2/3, the precommit
set after) exceeds 1/3 of the total, the episode is reported as
``halt_reason="quorum_lost"`` (with the missing power and the bitmap in
the log line and the debugdump bundle) instead of the generic
``"stalled"`` — so an intentional isolation window produces an
attributable record, not an uninformative stall bundle.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

logger = logging.getLogger("tmtpu.watchdog")


class ConsensusWatchdog:
    def __init__(self, cs, stall_timeout_s: float,
                 metrics=None, dump_dir: Optional[str] = None,
                 dump_node=None, check_interval_s: Optional[float] = None,
                 height_fn=None):
        """``cs`` is the ConsensusState to observe; ``metrics`` a
        ConsensusMetrics (or None); ``dump_node`` whatever should be
        handed to debugdump.write_dump (a Node, or a shim with
        consensus_state/switch attributes, or None for stacks-only).
        ``height_fn`` overrides the progress probe — the node passes the
        block-store height, which advances during fast-sync too;
        ConsensusState.state only moves after switch_to_consensus, so
        sampling it alone would flag a >T-second block-sync as a stall."""
        self.cs = cs
        self._height_fn = height_fn
        self.stall_timeout_s = stall_timeout_s
        self.metrics = metrics
        self.dump_dir = dump_dir
        self.dump_node = dump_node
        self.check_interval_s = (check_interval_s if check_interval_s
                                 is not None
                                 else max(0.25, stall_timeout_s / 4))
        self.stalls = 0            # episodes observed (tests read this)
        self.last_halt_reason: Optional[str] = None  # "stalled"/"quorum_lost"
        self.last_halt_detail: dict = {}
        self.last_dump_path: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        self._last_height = -1
        self._last_advance_t = 0.0
        self._in_stall = False

    async def start(self) -> None:
        self._last_height = self._height()
        self._last_advance_t = time.monotonic()
        self._task = asyncio.create_task(self._run(),
                                         name=f"cs-watchdog-{id(self)}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _height(self) -> int:
        if self._height_fn is not None:
            return self._height_fn()
        return self.cs.state.last_block_height

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            h = self._height()
            now = time.monotonic()
            if h != self._last_height:
                self._last_height = h
                self._last_advance_t = now
                if self._in_stall:
                    logger.warning("consensus resumed at height %d after "
                                   "stall", h)
                    self._in_stall = False
                continue
            if (not self._in_stall
                    and now - self._last_advance_t >= self.stall_timeout_s):
                self._in_stall = True
                self.stalls += 1
                self._report(h, now - self._last_advance_t)

    def classify_halt(self) -> "tuple[str, dict]":
        """Classify the current halt from the live round's vote sets:
        ``("quorum_lost", detail)`` when the voting power absent from the
        stage blocking the round exceeds 1/3 of the total (no quorum can
        form — the expected consequence of an isolation window), else
        ``("stalled", detail)``. ``detail`` carries the blocking stage
        and the per-validator vote bitmap rows for the debugdump bundle;
        it is empty only when the round state isn't inspectable."""
        rs = getattr(self.cs, "rs", None)
        votes = getattr(rs, "votes", None)
        vals = getattr(rs, "validators", None)
        if rs is None or votes is None or vals is None:
            return "stalled", {}
        round_ = getattr(rs, "round", 0)
        try:
            prevotes = votes.prevotes(round_)
            precommits = votes.precommits(round_)
            total = vals.total_voting_power()
            if not total:
                return "stalled", {}
            pv_power = prevotes.sum if prevotes is not None else 0
            pc_power = precommits.sum if precommits is not None else 0
            pv_bits = prevotes.bit_array() if prevotes is not None else None
            pc_bits = (precommits.bit_array()
                       if precommits is not None else None)
            rows = []
            for i, val in enumerate(vals.validators):
                rows.append({
                    "index": i,
                    "address": val.address.hex(),
                    "power": val.voting_power,
                    "prevote": bool(pv_bits is not None
                                    and pv_bits.get_index(i)),
                    "precommit": bool(pc_bits is not None
                                      and pc_bits.get_index(i)),
                })
        except Exception:
            logger.exception("halt classification failed; "
                             "falling back to generic stall")
            return "stalled", {}
        # the missing power is measured against the stage BLOCKING the
        # round, not the best-populated set: a cut landing between the
        # prevote and precommit quorums leaves a full prevote set behind
        # (delivered pre-cut) while the precommits can never reach 2/3 —
        # that window is still a quorum loss
        if pv_power * 3 > total * 2:
            blocking, present = "precommit", pc_power
        else:
            blocking, present = "prevote", pv_power
        missing = total - present
        detail = {
            "height": getattr(rs, "height", -1),
            "round": round_,
            "total_power": total,
            "prevote_power": pv_power,
            "precommit_power": pc_power,
            "blocking_stage": blocking,
            "missing_power": missing,
            "validators": rows,
        }
        reason = "quorum_lost" if missing * 3 > total else "stalled"
        return reason, detail

    def _report(self, height: int, idle_s: float) -> None:
        rs = getattr(self.cs, "rs", None)
        reason, detail = self.classify_halt()
        self.last_halt_reason = reason
        self.last_halt_detail = detail
        if reason == "quorum_lost":
            logger.critical(
                "consensus halted, quorum lost: no commit for %.1fs "
                "(height=%d round=%s step=%s) — %d/%d voting power "
                "missing from the round's vote sets (>1/3); liveness "
                "halt is EXPECTED until the power returns",
                idle_s, height, getattr(rs, "round", "?"),
                getattr(rs, "step", "?"), detail.get("missing_power", -1),
                detail.get("total_power", -1))
        else:
            logger.critical(
                "consensus stalled: no commit for %.1fs (height=%d round=%s "
                "step=%s)", idle_s, height,
                getattr(rs, "round", "?"), getattr(rs, "step", "?"))
        if self.metrics is not None:
            self.metrics.consensus_stalled_total.inc()
        if self.dump_dir:
            try:
                from ..libs.debugdump import write_dump

                out = os.path.join(self.dump_dir,
                                   f"debug-{reason.replace('_', '-')}-"
                                   f"{int(time.time())}")
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                self.last_dump_path = write_dump(
                    out, node=self.dump_node, loop=loop,
                    extras={"halt_reason": reason, "idle_s": round(idle_s, 3),
                            "halt_detail": detail})
                logger.critical("%s debugdump written to %s", reason,
                                self.last_dump_path)
            except Exception:
                logger.exception("failed writing stall debugdump")
