"""Consensus reactor: gossips round state, block parts, and votes over four
p2p channels (reference consensus/reactor.go — State=0x20 Data=0x21 Vote=0x22
VoteSetBits=0x23, three gossip tasks per peer + broadcast listeners).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from ..libs.bits import BitArray
from ..libs.trace import tracer
from ..p2p import (
    DATA_CHANNEL,
    STATE_CHANNEL,
    VOTE_CHANNEL,
    VOTE_SET_BITS_CHANNEL,
)
from ..p2p.base import ChannelDescriptor, Peer, Reactor
from ..types.basic import BlockID, PartSetHeader, SignedMsgType
from ..types.vote import Vote
from .msgs import (
    BlockPartMessageWire,
    HasVoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessageWire,
    ProposalPOLMessage,
    VoteMessageWire,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    WireEncodeCache,
    decode_msg,
    encode_msg,
)
from .round_state import RoundState, RoundStep
from .state import BlockPartMessage, ConsensusState, ProposalMessage, VoteMessage

logger = logging.getLogger("tmtpu.cs.reactor")

# cap on detached preverify-and-forward tasks before peer backpressure kicks in
MAX_INFLIGHT_PREVERIFY = 1024


class _Waker:
    """Level-triggered wakeup for one gossip routine.

    ``wake()`` sets the event; ``wait()`` returns True as soon as any wake
    since the last wait fired (including during the routine's preceding
    work burst — no lost wakeups), or False when the fallback sleep cap
    expired with no signal. The configured peer_gossip_sleep_duration thus
    becomes an upper bound on gossip staleness instead of its clock.
    """

    __slots__ = ("_event",)

    def __init__(self):
        self._event = asyncio.Event()

    def wake(self) -> None:
        self._event.set()

    async def wait(self, timeout: float) -> bool:
        if not self._event.is_set():
            try:
                await asyncio.wait_for(self._event.wait(), timeout)
            except asyncio.TimeoutError:
                return False
        self._event.clear()
        return True


class PeerRoundState:
    """What we know about a peer's consensus state (consensus/types/peer_round_state.go)."""

    def __init__(self):
        self.height = 0
        self.round = -1
        self.step = RoundStep.NEW_HEIGHT
        self.start_time_ns = 0
        self.proposal = False
        self.proposal_block_part_set_header = PartSetHeader()
        self.proposal_block_parts: Optional[BitArray] = None
        self.proposal_pol_round = -1
        self.proposal_pol: Optional[BitArray] = None
        self.prevotes: Optional[BitArray] = None
        self.precommits: Optional[BitArray] = None
        self.last_commit_round = -1
        self.last_commit: Optional[BitArray] = None
        self.catchup_commit_round = -1
        self.catchup_commit: Optional[BitArray] = None


class PeerState:
    """(consensus/reactor.go:1028 PeerState)"""

    def __init__(self, peer: Peer):
        self.peer = peer
        self.prs = PeerRoundState()
        self.last_recv_t = time.monotonic()

    def note_recv(self) -> None:
        self.last_recv_t = time.monotonic()

    def refresh_if_stalled(self, stall_s: float) -> bool:
        """Self-healing gossip: downgrade a silent peer's delivery bitmaps
        from facts to guesses. Gossip marks a vote/part as delivered when
        it SENDS it (reactor.go PickSendVote semantics) — sound over the
        reliable TCP transport, but a lossy or blackholed link (partition,
        dying relay, chaos LinkPolicy) eats sends silently and the bitmaps
        then claim the peer has data it never saw: catchup stops and the
        link wedges permanently. After ``stall_s`` without a single
        message from the peer, clear what we think we delivered so the
        gossip routines re-send — duplicates are cheap (PartSet/VoteSet
        dedup), a poisoned bitmap is a liveness hole. Height/round/step
        are kept: those came FROM the peer."""
        if stall_s <= 0:
            return False
        now = time.monotonic()
        if now - self.last_recv_t < stall_s:
            return False
        self.last_recv_t = now  # one refresh per silent interval
        prs = self.prs
        prs.proposal = False
        if prs.proposal_block_parts is not None:
            prs.proposal_block_parts = BitArray(
                prs.proposal_block_parts.size())
        for name in ("prevotes", "precommits", "last_commit",
                     "catchup_commit", "proposal_pol"):
            ba = getattr(prs, name)
            if ba is not None:
                setattr(prs, name, BitArray(ba.size()))
        return True

    # -- updates from messages --------------------------------------------

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        prs = self.prs
        # Ignore duplicates or decreases (reactor.go ApplyNewRoundStepMessage
        # CompareHRS guard) — otherwise a byzantine peer can wipe our
        # bookkeeping and trigger bandwidth-amplifying re-gossip.
        if _compare_hrs(msg.height, msg.round,
                        RoundStep(msg.step) if msg.step else RoundStep.NEW_HEIGHT,
                        prs.height, prs.round, prs.step) <= 0:
            return
        ps_height, ps_round = prs.height, prs.round
        ps_catchup_commit_round = prs.catchup_commit_round
        ps_catchup_commit = prs.catchup_commit

        prs.height = msg.height
        prs.round = msg.round
        prs.step = RoundStep(msg.step) if msg.step else RoundStep.NEW_HEIGHT
        prs.start_time_ns = time.time_ns() - msg.seconds_since_start_time * 1_000_000_000
        if ps_height != msg.height or ps_round != msg.round:
            prs.proposal = False
            prs.proposal_block_part_set_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
        if (ps_height == msg.height and ps_round != msg.round
                and msg.round == ps_catchup_commit_round):
            prs.precommits = ps_catchup_commit
        if ps_height != msg.height:
            if ps_height + 1 == msg.height and ps_round == msg.last_commit_round:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = prs.precommits
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.catchup_commit_round = -1
            prs.catchup_commit = None

    def apply_new_valid_block(self, msg: NewValidBlockMessage) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round != msg.round and not msg.is_commit:
            return
        prs.proposal_block_part_set_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def set_has_proposal(self, proposal) -> None:
        prs = self.prs
        if prs.height != proposal.height or prs.round != proposal.round:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is not None:
            return  # NewValidBlock already set this
        prs.proposal_block_part_set_header = proposal.block_id.part_set_header
        prs.proposal_block_parts = BitArray(proposal.block_id.part_set_header.total)
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        prs = self.prs
        if prs.height != msg.height or prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        prs = self.prs
        if prs.height != height or prs.round != round_:
            return
        if prs.proposal_block_parts is not None:
            prs.proposal_block_parts.set_index(index, True)

    def apply_has_vote(self, msg: HasVoteMessage) -> None:
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def set_has_vote(self, height: int, round_: int, type_: SignedMsgType,
                     index: int) -> None:
        ba = self._votes_bit_array(height, round_, type_)
        if ba is not None:
            ba.set_index(index, True)

    def _votes_bit_array(self, height: int, round_: int,
                         type_: SignedMsgType) -> Optional[BitArray]:
        """(reactor.go PeerState.getVoteBitArray)"""
        prs = self.prs
        is_prevote = type_ == SignedMsgType.PREVOTE
        if prs.height == height:
            if prs.round == round_:
                return prs.prevotes if is_prevote else prs.precommits
            if prs.catchup_commit_round == round_ and not is_prevote:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and is_prevote:
                return prs.proposal_pol
            return None
        if prs.height == height + 1 and prs.last_commit_round == round_ \
                and not is_prevote:
            return prs.last_commit
        return None

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        prs = self.prs
        if prs.height == height:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
            if prs.catchup_commit is None:
                prs.catchup_commit = BitArray(num_validators)
            if prs.proposal_pol is None:
                prs.proposal_pol = BitArray(num_validators)
        elif prs.height == height + 1:
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(self, height: int, round_: int,
                                    num_validators: int) -> None:
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        if round_ == prs.round:
            prs.catchup_commit = prs.precommits
        else:
            prs.catchup_commit = BitArray(num_validators)

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage, our_votes: Optional[BitArray]) -> None:
        """(reactor.go ApplyVoteSetBitsMessage): keep what we know the peer has
        beyond our own votes, and take the peer's word for the overlap —
        NEVER credit the peer with our votes."""
        ba = self._votes_bit_array(msg.height, msg.round, msg.type)
        if ba is not None:
            if our_votes is not None:
                other_votes = ba.sub(our_votes)
                ba.update(other_votes.or_(msg.votes))
            else:
                ba.update(msg.votes)

    # -- vote picking (reactor.go:1149 PickSendVote) -----------------------

    def pick_vote_to_send(self, votes: "_VoteSetReader") -> Optional[Vote]:
        """(reactor.go:1169 PickVoteToSend) — lazily sets up catchup-commit
        and vote bit arrays from the reader before picking."""
        if votes.size() == 0:
            return None
        height, round_, type_ = votes.height, votes.round, votes.type_
        if votes.is_commit():
            self.ensure_catchup_commit_round(height, round_, votes.size())
        self.ensure_vote_bit_arrays(height, votes.size())
        ba = self._votes_bit_array(height, round_, type_)
        if ba is None:
            return None
        missing = votes.bit_array().sub(ba)
        idx, ok = missing.pick_random()
        if not ok:
            return None
        return votes.get_by_index(idx)


class _VoteSetReader:
    """Uniform view over VoteSet and Commit for gossip (reference VoteSetReader)."""

    def __init__(self, height: int, round_: int, type_: SignedMsgType, vote_set=None,
                 commit=None):
        self.height = height
        self.round = round_
        self.type_ = type_
        self._vote_set = vote_set
        self._commit = commit

    @staticmethod
    def from_vote_set(vs) -> "_VoteSetReader":
        return _VoteSetReader(vs.height, vs.round, vs.signed_msg_type, vote_set=vs)

    @staticmethod
    def from_commit(commit) -> "_VoteSetReader":
        return _VoteSetReader(commit.height, commit.round, SignedMsgType.PRECOMMIT,
                              commit=commit)

    def size(self) -> int:
        if self._vote_set is not None:
            return self._vote_set.size()
        return self._commit.size()

    def is_commit(self) -> bool:
        return self._commit is not None

    def bit_array(self) -> BitArray:
        if self._vote_set is not None:
            return self._vote_set.bit_array()
        if hasattr(self._commit, "agg_sig"):
            # no per-validator votes to offer — peers catch up via block sync
            return BitArray(self._commit.size())
        ba = BitArray(len(self._commit.signatures))
        for i, cs in enumerate(self._commit.signatures):
            ba.set_index(i, not cs.absent())
        return ba

    def get_by_index(self, idx: int) -> Optional[Vote]:
        if self._vote_set is not None:
            return self._vote_set.get_by_index(idx)
        if hasattr(self._commit, "agg_sig"):
            return None
        if self._commit.signatures[idx].absent():
            return None
        return self._commit.get_vote(idx)


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState, wait_sync: bool = False):
        super().__init__("CONSENSUS")
        self.cs = cs
        self.wait_sync = wait_sync  # True while fast sync runs
        self._peer_states: Dict[str, PeerState] = {}
        self._gossip_tasks: Dict[str, List[asyncio.Task]] = {}
        # strong refs to detached preverify-and-forward tasks (the loop keeps
        # only weak refs; a GC'd task would drop the vote silently)
        self._inflight: set = set()
        # event-driven gossip: per-peer wakers for the data/votes routines,
        # signaled on round-state transitions, new proposal data, and new
        # votes (and on inbound peer-state changes for that peer)
        self._wakers: Dict[str, Dict[str, _Waker]] = {}
        # one encode per message content, shared across peers and iterations
        self._encode_cache = WireEncodeCache()
        self._prune_height = 0
        # subscribe to internal state events for broadcasts
        cs.new_round_step_listeners.append(self._broadcast_new_round_step)
        cs.valid_block_listeners.append(self._broadcast_new_valid_block)
        cs.vote_listeners.append(self._broadcast_has_vote)
        cs.equivocation_listeners.append(self._broadcast_vote_directly)
        cs.proposal_data_listeners.append(self._wake_data_routines)

    def set_metrics(self, metrics) -> None:
        """Wire ConsensusMetrics into the reactor-side hot paths. The gossip
        wakeup/poll counters read ``cs.metrics`` directly; the encode cache
        keeps its own hook because it has no cs reference."""
        self._encode_cache.metrics = metrics

    def get_channels(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=7),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1),
        ]

    # -- peer lifecycle ----------------------------------------------------

    def init_peer(self, peer: Peer) -> Peer:
        self._peer_states[peer.id] = PeerState(peer)
        return peer

    async def add_peer(self, peer: Peer) -> None:
        ps = self._peer_states[peer.id]
        if self.cs.config.peer_gossip_event_wakeups:
            self._wakers[peer.id] = {"data": _Waker(), "votes": _Waker()}
        tasks = [
            asyncio.create_task(self._gossip_data_routine(peer, ps)),
            asyncio.create_task(self._gossip_votes_routine(peer, ps)),
            asyncio.create_task(self._query_maj23_routine(peer, ps)),
        ]
        self._gossip_tasks[peer.id] = tasks
        if not self.wait_sync:
            self._send_new_round_step(peer)

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        for t in self._gossip_tasks.pop(peer.id, []):
            t.cancel()
        self._peer_states.pop(peer.id, None)
        self._wakers.pop(peer.id, None)

    async def stop(self) -> None:
        for tasks in self._gossip_tasks.values():
            for t in tasks:
                t.cancel()
        self._gossip_tasks.clear()
        self._wakers.clear()

    # -- gossip wakeups ----------------------------------------------------

    def _wake_gossip(self, routine: Optional[str] = None) -> None:
        """Wake every peer's gossip routines (or just one routine kind)."""
        for wakers in self._wakers.values():
            if routine is None:
                for w in wakers.values():
                    w.wake()
            else:
                w = wakers.get(routine)
                if w is not None:
                    w.wake()

    def _wake_data_routines(self) -> None:
        self._wake_gossip("data")

    def _wake_peer(self, peer_id: str) -> None:
        """An inbound message changed what this peer is known to have."""
        for w in self._wakers.get(peer_id, {}).values():
            w.wake()

    def _maybe_refresh_peer(self, ps: PeerState) -> None:
        """Self-healing gossip: if the peer has been silent past
        gossip_stall_refresh_s AND could still need something from us,
        clear its delivery bitmaps so both gossip routines re-send (see
        PeerState.refresh_if_stalled). A peer behind our height always
        qualifies (the classic post-heal catchup case). A peer AT our
        height qualifies only while we are inside an active round
        ourselves: a healed quorum-loss window leaves every node wedged
        at the same height in PREVOTE/PRECOMMIT — a step with NO timeout
        until 2/3-any arrives, so the "round timeouts reset the vote
        bitmaps via NewRoundStep" escape hatch never fires and the
        delivery bitmaps (poisoned by sends the blocked links ate) wedge
        the fleet permanently. The NEW_HEIGHT/COMMIT exclusion keeps a
        healthy net that idles between txs quiet: idle peers sit at
        NEW_HEIGHT needing nothing re-sent."""
        rs = self.cs.rs
        if ps.prs.height > rs.height:
            return
        if (ps.prs.height == rs.height
                and rs.step in (RoundStep.NEW_HEIGHT, RoundStep.COMMIT)):
            return
        if ps.refresh_if_stalled(self.cs.config.gossip_stall_refresh_s):
            m = self.cs.metrics
            if m is not None:
                m.gossip_peer_refreshes_total.inc()
            self._wake_peer(ps.peer.id)

    async def _gossip_idle(self, waker: Optional[_Waker], sleep: float,
                           routine: str) -> None:
        """Idle until an event wakeup or the fallback sleep cap."""
        if waker is None:
            await asyncio.sleep(sleep)
            return
        if tracer.enabled:
            with tracer.span("gossip_idle", routine=routine,
                             height=self.cs.rs.height):
                woke = await waker.wait(sleep)
        else:
            woke = await waker.wait(sleep)
        m = self.cs.metrics
        if m is not None:
            (m.gossip_wakeups_total if woke
             else m.gossip_polls_total).labels(routine).inc()

    # -- switch-to-consensus (reactor.go:108) ------------------------------

    def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        if state.last_block_height > 0:
            self.cs.reconstruct_last_commit(state)
        self.cs.update_to_state(state)
        self.wait_sync = False
        self._broadcast_new_round_step(self.cs.rs)
        if self.cs._receive_task is None:
            # the state machine was held back while sync ran (reference
            # reactor.go:108 SwitchToConsensus → conS.Start). Keep a strong
            # reference: the event loop holds only weak refs to tasks, and a
            # GC'd wrapper would silently drop consensus startup.
            self._start_task = asyncio.create_task(self.cs.start())

    # -- inbound -----------------------------------------------------------

    def _broadcast_vote_directly(self, vote) -> None:
        """Maverick support: push a (possibly equivocating) vote to every
        peer on the vote channel, bypassing vote-set gossip."""
        if self.switch is not None:
            self.switch.broadcast(VOTE_CHANNEL, self._encode_cache.vote(vote))

    async def _preverify_and_forward(self, vote, peer_id: str) -> None:
        """Pre-verify then enqueue to the state machine. Vote delivery order
        is irrelevant (VoteSet is a set keyed by validator index)."""
        await self._preverify_vote(vote)
        await self.cs.add_peer_msg(VoteMessage(vote), peer_id)

    async def _preverify_vote(self, vote) -> None:
        """Feed the vote's signature into the micro-batch verifier so the
        state machine's VoteSet.add_vote hits the verdict cache. Best-effort:
        any miss (unknown height/index) falls back to the host scalar path
        inside VoteSet — decisions are identical either way."""
        try:
            rs = self.cs.rs
            if vote.height == rs.height and rs.validators is not None:
                vals = rs.validators
            elif (vote.height == rs.height - 1
                  and rs.last_commit is not None):
                vals = rs.last_commit.val_set
            else:
                return
            if not (0 <= vote.validator_index < vals.size()):
                return
            _addr, val = vals.get_by_index(vote.validator_index)
            if val is None or val.pub_key.address() != vote.validator_address:
                return
            await self.cs.vote_verifier.preverify(
                val.pub_key, vote.sign_bytes(self.cs.state.chain_id),
                vote.signature)
        except Exception:  # never let pre-verification break gossip
            logger.debug("vote preverify skipped", exc_info=True)

    async def receive(self, channel_id: int, peer: Peer, msg_bytes: bytes) -> None:
        msg = decode_msg(msg_bytes)
        ps = self._peer_states.get(peer.id)
        if ps is None:
            return
        ps.note_recv()
        rs = self.cs.rs

        if channel_id == STATE_CHANNEL:
            if isinstance(msg, NewRoundStepMessage):
                _validate_nrs(msg, self.cs.state.initial_height)
                ps.apply_new_round_step(msg)
                # the peer moved: what we can usefully send it changed
                self._wake_peer(peer.id)
            elif isinstance(msg, NewValidBlockMessage):
                ps.apply_new_valid_block(msg)
                self._wake_peer(peer.id)
            elif isinstance(msg, HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, VoteSetMaj23Message):
                if rs.height != msg.height:
                    return
                try:
                    # creates the round's vote sets if absent (HeightVoteSet
                    # SetPeerMaj23, like the reference's cs.Votes path)
                    rs.votes.set_peer_maj23(msg.round, msg.type, peer.id,
                                            msg.block_id)
                except Exception as e:
                    await self.switch.stop_peer_for_error(peer, str(e))
                    return
                vote_set = (rs.votes.prevotes(msg.round)
                            if msg.type == SignedMsgType.PREVOTE
                            else rs.votes.precommits(msg.round))
                # respond with VoteSetBits on the VoteSetBits channel
                if vote_set is not None:
                    our = vote_set.bit_array_by_block_id(msg.block_id)
                    peer.try_send(VOTE_SET_BITS_CHANNEL, encode_msg(VoteSetBitsMessage(
                        msg.height, msg.round, msg.type, msg.block_id,
                        our or BitArray(0))))
        elif channel_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, ProposalMessageWire):
                ps.set_has_proposal(msg.proposal)
                # stage-timeline aux mark at WIRE receipt: the gap to the
                # state machine's proposal_received mark is queue delay
                self.cs.timeline.note_wire_proposal(msg.proposal.height)
                await self.cs.add_peer_msg(ProposalMessage(msg.proposal), peer.id)
            elif isinstance(msg, ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
                self._wake_peer(peer.id)
            elif isinstance(msg, BlockPartMessageWire):
                ps.set_has_proposal_block_part(msg.height, msg.round, msg.part.index)
                await self.cs.add_peer_msg(
                    BlockPartMessage(msg.height, msg.round, msg.part), peer.id)
        elif channel_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, VoteMessageWire):
                height = self.cs.rs.height
                val_size = self.cs.rs.validators.size() if self.cs.rs.validators else 0
                last_size = (self.cs.rs.last_commit.size()
                             if self.cs.rs.last_commit else 0)
                ps.ensure_vote_bit_arrays(height, val_size)
                ps.ensure_vote_bit_arrays(height - 1, last_size)
                ps.set_has_vote(msg.vote.height, msg.vote.round, msg.vote.type,
                                msg.vote.validator_index)
                # HOT LOOP #1: pre-verify the signature, then forward — as a
                # detached task so this peer's dispatch loop keeps reading
                # while the verifier accumulates a batch across peers
                # (vote_set.go:205 equivalent; crypto/vote_batcher.py).
                # Correctness never depends on it: a cache miss in VoteSet
                # falls back to the host scalar verify.
                if len(self._inflight) < MAX_INFLIGHT_PREVERIFY:
                    t = asyncio.create_task(
                        self._preverify_and_forward(msg.vote, peer.id))
                    self._inflight.add(t)
                    t.add_done_callback(self._inflight.discard)
                else:
                    # backpressure: a vote-flooding peer must not grow the
                    # task set unboundedly — block its dispatch loop (the
                    # bounded cs queue then applies, as before the change)
                    await self._preverify_and_forward(msg.vote, peer.id)
        elif channel_id == VOTE_SET_BITS_CHANNEL:
            if isinstance(msg, VoteSetBitsMessage):
                if rs.height == msg.height:
                    vote_set = (rs.votes.prevotes(msg.round)
                                if msg.type == SignedMsgType.PREVOTE
                                else rs.votes.precommits(msg.round))
                    our = vote_set.bit_array_by_block_id(msg.block_id) if vote_set else None
                    ps.apply_vote_set_bits(msg, our)
                else:
                    ps.apply_vote_set_bits(msg, None)

    # -- broadcasts (reactor.go:430 subscribeToBroadcastEvents) ------------

    def _nrs_message(self, rs) -> NewRoundStepMessage:
        return NewRoundStepMessage(
            height=rs.height, round=rs.round, step=int(rs.step),
            seconds_since_start_time=max(0, (time.time_ns() - rs.start_time_ns)
                                         // 1_000_000_000),
            last_commit_round=(rs.last_commit.round if rs.last_commit is not None
                               else -1),
        )

    def _broadcast_new_round_step(self, rs) -> None:
        if rs.height > self._prune_height:
            # height advanced: drop encode-cache entries that fell out of
            # the live gossip window (height-keyed invalidation)
            self._prune_height = rs.height
            self._encode_cache.prune_below(rs.height - 1)
        self._wake_gossip()
        if self.switch is not None:
            self.switch.broadcast(STATE_CHANNEL, encode_msg(self._nrs_message(rs)))

    def _broadcast_new_valid_block(self, rs) -> None:
        self._wake_gossip()
        if self.switch is None:
            return
        psh = (rs.proposal_block_parts.header() if rs.proposal_block_parts
               else PartSetHeader())
        ba = (rs.proposal_block_parts.parts_bit_array.copy()
              if rs.proposal_block_parts else BitArray(0))
        self.switch.broadcast(STATE_CHANNEL, encode_msg(NewValidBlockMessage(
            rs.height, rs.round, psh, ba, rs.step == RoundStep.COMMIT)))

    def _broadcast_has_vote(self, vote: Vote) -> None:
        self._wake_gossip("votes")
        if self.switch is not None:
            self.switch.broadcast(STATE_CHANNEL, encode_msg(HasVoteMessage(
                vote.height, vote.round, vote.type, vote.validator_index)))

    def _send_new_round_step(self, peer: Peer) -> None:
        peer.try_send(STATE_CHANNEL, encode_msg(self._nrs_message(self.cs.rs)))

    # -- gossip: data (reactor.go:559 gossipDataRoutine) -------------------

    async def _gossip_data_routine(self, peer: Peer, ps: PeerState) -> None:
        sleep = self.cs.config.peer_gossip_sleep_duration
        waker = self._wakers.get(peer.id, {}).get("data")
        try:
            while peer.is_running():
                self._maybe_refresh_peer(ps)
                rs = self.cs.rs
                prs = ps.prs

                # send proposal block parts the peer lacks
                if (rs.proposal_block_parts is not None
                        and rs.proposal_block_parts.header() == prs.proposal_block_part_set_header
                        and prs.proposal_block_parts is not None):
                    missing = rs.proposal_block_parts.parts_bit_array.sub(
                        prs.proposal_block_parts)
                    index, ok = missing.pick_random()
                    if ok:
                        part = rs.proposal_block_parts.get_part(index)
                        if peer.try_send(DATA_CHANNEL, self._encode_cache.block_part(
                                rs.height, rs.round,
                                prs.proposal_block_part_set_header.hash, part)):
                            ps.set_has_proposal_block_part(prs.height, prs.round, index)
                        await asyncio.sleep(0)
                        continue

                # peer is on an earlier height: catch them up from block store
                block_store_base = self.cs.block_store.base()
                if (0 < prs.height < rs.height
                        and prs.height >= block_store_base):
                    if await self._gossip_catchup_part(peer, ps):
                        continue
                    await self._gossip_idle(waker, sleep, "data")
                    continue

                if rs.height != prs.height or rs.round != prs.round:
                    await self._gossip_idle(waker, sleep, "data")
                    continue

                # send the Proposal (+ POL) if the peer lacks it
                if rs.proposal is not None and not prs.proposal:
                    if peer.try_send(DATA_CHANNEL,
                                     self._encode_cache.proposal(rs.proposal)):
                        ps.set_has_proposal(rs.proposal)
                    if 0 <= rs.proposal.pol_round:
                        pol = rs.votes.prevotes(rs.proposal.pol_round)
                        if pol is not None:
                            peer.try_send(DATA_CHANNEL, encode_msg(ProposalPOLMessage(
                                rs.height, rs.proposal.pol_round, pol.bit_array())))
                    await asyncio.sleep(0)
                    continue

                await self._gossip_idle(waker, sleep, "data")
        except asyncio.CancelledError:
            pass

    async def _gossip_catchup_part(self, peer: Peer, ps: PeerState) -> bool:
        """Send one missing part of an old block (reactor.go gossipDataForCatchup)."""
        prs = ps.prs
        if prs.proposal_block_parts is None:
            # init from stored block meta
            meta = self.cs.block_store.load_block_meta(prs.height)
            if meta is None:
                return False
            ps.prs.proposal_block_part_set_header = meta.block_id.part_set_header
            ps.prs.proposal_block_parts = BitArray(meta.block_id.part_set_header.total)
        missing = BitArray(prs.proposal_block_part_set_header.total)
        missing.update(prs.proposal_block_parts.not_())
        index, ok = missing.pick_random()
        if not ok:
            return False
        part = self.cs.block_store.load_block_part(prs.height, index)
        if part is None:
            return False
        if peer.try_send(DATA_CHANNEL, self._encode_cache.block_part(
                prs.height, prs.round,
                prs.proposal_block_part_set_header.hash, part)):
            prs.proposal_block_parts.set_index(index, True)
            return True
        return False

    # -- gossip: votes (reactor.go:716 gossipVotesRoutine) -----------------

    async def _gossip_votes_routine(self, peer: Peer, ps: PeerState) -> None:
        sleep = self.cs.config.peer_gossip_sleep_duration
        waker = self._wakers.get(peer.id, {}).get("votes")
        try:
            while peer.is_running():
                self._maybe_refresh_peer(ps)
                rs = self.cs.rs
                prs = ps.prs
                if rs.height == prs.height:
                    if self._gossip_votes_for_height(rs, ps, peer):
                        await asyncio.sleep(0)
                        continue
                elif (prs.height != 0 and rs.height == prs.height + 1
                      and rs.last_commit is not None):
                    if self._pick_send_vote(
                            peer, ps, _VoteSetReader.from_vote_set(rs.last_commit)):
                        await asyncio.sleep(0)
                        continue
                elif (prs.height != 0 and rs.height >= prs.height + 2
                      and self.cs.block_store.base() <= prs.height
                      <= self.cs.block_store.height()):
                    commit = self.cs.block_store.load_block_commit(prs.height)
                    if commit is not None and self._pick_send_vote(
                            peer, ps, _VoteSetReader.from_commit(commit)):
                        await asyncio.sleep(0)
                        continue
                await self._gossip_idle(waker, sleep, "votes")
        except asyncio.CancelledError:
            pass

    def _gossip_votes_for_height(self, rs, ps: PeerState, peer: Peer) -> bool:
        """(reactor.go:789)"""
        prs = ps.prs
        val_size = rs.validators.size() if rs.validators else 0
        ps.ensure_vote_bit_arrays(prs.height, val_size)

        # last commit while peer catches up to NewHeight
        if (prs.step == RoundStep.NEW_HEIGHT and rs.last_commit is not None
                and self._pick_send_vote(
                    peer, ps, _VoteSetReader.from_vote_set(rs.last_commit))):
            return True
        # POL prevotes
        if prs.step <= RoundStep.PROPOSE and 0 <= prs.proposal_pol_round:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._pick_send_vote(
                    peer, ps, _VoteSetReader.from_vote_set(pol)):
                return True
        # prevotes for peer's round
        if prs.step <= RoundStep.PREVOTE_WAIT and 0 <= prs.round <= rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and self._pick_send_vote(
                    peer, ps, _VoteSetReader.from_vote_set(pv)):
                return True
        # precommits for peer's round
        if prs.step <= RoundStep.PRECOMMIT_WAIT and 0 <= prs.round <= rs.round:
            pc = rs.votes.precommits(prs.round)
            if pc is not None and self._pick_send_vote(
                    peer, ps, _VoteSetReader.from_vote_set(pc)):
                return True
        if 0 <= prs.proposal_pol_round:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._pick_send_vote(
                    peer, ps, _VoteSetReader.from_vote_set(pol)):
                return True
        return False

    def _pick_send_vote(self, peer: Peer, ps: PeerState,
                        reader: _VoteSetReader) -> bool:
        vote = ps.pick_vote_to_send(reader)
        if vote is None:
            return False
        if peer.try_send(VOTE_CHANNEL, self._encode_cache.vote(vote)):
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
            return True
        return False

    # -- maj23 queries (reactor.go:849 queryMaj23Routine) ------------------

    async def _query_maj23_routine(self, peer: Peer, ps: PeerState) -> None:
        sleep = self.cs.config.peer_query_maj23_sleep_duration
        try:
            while peer.is_running():
                await asyncio.sleep(sleep)
                rs = self.cs.rs
                prs = ps.prs
                if rs.height != prs.height or rs.votes is None:
                    continue
                for type_, vs in ((SignedMsgType.PREVOTE, rs.votes.prevotes(prs.round)),
                                  (SignedMsgType.PRECOMMIT, rs.votes.precommits(prs.round))):
                    if vs is None or prs.round < 0:
                        continue
                    maj23, ok = vs.two_thirds_majority()
                    if ok:
                        peer.try_send(STATE_CHANNEL, encode_msg(VoteSetMaj23Message(
                            prs.height, prs.round, type_, maj23)))
        except asyncio.CancelledError:
            pass


def _compare_hrs(h1: int, r1: int, s1: RoundStep,
                 h2: int, r2: int, s2: RoundStep) -> int:
    """(consensus/types/peer_round_state.go CompareHRS semantics)"""
    if (h1, r1, int(s1)) < (h2, r2, int(s2)):
        return -1
    if (h1, r1, int(s1)) == (h2, r2, int(s2)):
        return 0
    return 1


def _validate_nrs(msg: NewRoundStepMessage, initial_height: int) -> None:
    if msg.height < initial_height and msg.height != 0:
        raise ValueError(f"invalid NewRoundStep height {msg.height}")
    if msg.round < 0 or int(msg.step) < 1 or int(msg.step) > 8:
        raise ValueError("invalid NewRoundStep round/step")
