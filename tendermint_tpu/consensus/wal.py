"""Consensus write-ahead log (reference consensus/wal.go:58).

Every consensus input is logged before it acts on the state machine; own
(internal) messages are fsynced. Framing mirrors the reference encoder
(wal.go:288): crc32(payload) u32 BE || length u32 BE || payload. Payload is a
JSON envelope {time_ns, type, data} — msg types: "vote", "proposal",
"block_part", "timeout", "end_height", "round_step" (EventDataRoundStep).
Size-rotated like libs/autofile.Group.

Group commit: ``with wal.group():`` defers the flush/fsync of every record
written inside to the context exit — one fsync covers the whole batch when
any record in it requires durability (own messages), so a proposal plus its
N block parts cost one disk sync instead of N+1. Record bytes and ordering
are identical to per-record writes; only the fsync schedule changes, and the
receive loop commits the group BEFORE acting on any message in it, which
preserves the reference rule that our own messages are durable before any
state transition can expose them to gossip (state.go:754,763).
"""

from __future__ import annotations

import contextlib
import errno
import json
import logging
import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from ..libs.fail import KilledAtFailPoint, fail_point
from ..libs.faults import faults
from ..libs.trace import tracer
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote

logger = logging.getLogger("tmtpu.wal")

MAX_MSG_SIZE_BYTES = 1024 * 1024  # 1MB (wal.go maxMsgSizeBytes)
DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # autofile group head rotation
DEFAULT_GROUP_LIMIT = 60 * 1024 * 1024

#: exit code for the fatal-fsync path (EX_IOERR from sysexits.h)
FSYNC_EXIT_CODE = 74


class FsyncError(BaseException):
    """A WAL fsync failed: durability of already-written records is
    UNKNOWN (fsyncgate: after a failed fsync the kernel may have dropped
    the dirty pages, and a later successful fsync proves nothing about
    them). BaseException on purpose — the consensus loop's defensive
    ``except Exception`` must not be able to swallow it and carry on
    treating the records as durable; like the reference's panic, the only
    safe continuation is a restart that replays the WAL from disk."""


def _injected_eio(site: str) -> OSError:
    return OSError(errno.EIO, f"injected fault at {site}")


@dataclass
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int  # RoundStep value


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class WALMessage:
    time_ns: int
    type: str
    data: dict


def _encode_vote(v: Vote) -> dict:
    return {"vote": v.encode().hex()}


def _encode_msg(msg, peer_id: str) -> Tuple[str, dict]:
    from .state import BlockPartMessage, ProposalMessage, VoteMessage

    if isinstance(msg, VoteMessage):
        return "vote", {"vote": msg.vote.encode().hex(), "peer": peer_id}
    if isinstance(msg, ProposalMessage):
        return "proposal", {"proposal": msg.proposal.encode().hex(), "peer": peer_id}
    if isinstance(msg, BlockPartMessage):
        return "block_part", {"height": msg.height, "round": msg.round,
                              "part": msg.part.encode().hex(), "peer": peer_id}
    raise ValueError(f"unsupported WAL message {type(msg)}")


class WAL:
    # class-level defaults so no-op/partial subclasses (NilWAL) and
    # long-lived instances share the group-commit surface without each
    # __init__ having to know about it
    _group_depth = 0
    _group_records = 0
    _group_sync = False
    _last_sync_t = 0.0
    #: fsync-even-without-a-durable-record deadline for grouped batches of
    #: purely external records (the reference never syncs those at all; the
    #: deadline only bounds how far an async tail can lag)
    sync_deadline_s = 0.05
    #: ConsensusMetrics (wal_fsyncs_total / wal_records_per_fsync /
    #: wal_fsync_seconds), wired by the node
    metrics = None
    #: what to do when os.fsync raises (fsyncgate semantics — continuing
    #: would record messages as durable that may not be): "exit" kills the
    #: process (a node restart replays the WAL, the reference's panic
    #: analog); "raise" surfaces FsyncError for in-process harnesses.
    #: Env override TMTPU_FSYNC_ERROR_POLICY for subprocess nets.
    fsync_error_policy = os.environ.get("TMTPU_FSYNC_ERROR_POLICY", "exit")

    #: repair-on-open accounting (crash-recovery plane): how many torn
    #: tails this instance truncated at open, and how many bytes went
    repairs = 0
    repaired_bytes = 0

    def __init__(self, path: str, head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
                 repair: bool = True):
        self.path = path
        self._head_size_limit = head_size_limit
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # a crash can leave a torn/garbage tail; appending after it would
        # strand every new record behind undecodable bytes (CRC-bounded
        # replay stops at the first bad frame), so repair BEFORE opening
        # for append. repair=False for read-only observers (cmd debug) —
        # truncating a file a LIVE node holds open for append would corrupt
        # it under the owner's feet.
        self.repaired_bytes = self._repair_tail(path) if repair else 0
        self.repairs = 1 if self.repaired_bytes else 0
        self._f = open(path, "ab")
        self._records_since_sync = 0
        # fresh WAL: write #ENDHEIGHT 0 so height-1 catchup replay has its
        # start marker (reference consensus/wal.go BaseWAL.OnStart)
        if self._f.tell() == 0 and not os.path.exists(f"{path}.0"):
            self.write_sync("end_height", {"height": 0})

    @staticmethod
    def _decodable_prefix_len(raw: bytes) -> int:
        """Byte length of the longest valid-record prefix of `raw` (same
        validity rule as iter_messages: framing + CRC + JSON envelope)."""
        pos = 0
        while pos + 8 <= len(raw):
            crc, ln = struct.unpack_from(">II", raw, pos)
            if ln > MAX_MSG_SIZE_BYTES or pos + 8 + ln > len(raw):
                break
            payload = raw[pos + 8:pos + 8 + ln]
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                break
            try:
                json.loads(payload.decode())
            except (ValueError, UnicodeDecodeError):
                break
            pos += 8 + ln
        return pos

    def _repair_tail(self, path: str) -> int:
        """Truncate any undecodable suffix of the head file so appended
        records stay replayable; returns bytes removed (0 = clean)."""
        if not os.path.exists(path):
            return 0
        with open(path, "rb") as f:
            raw = f.read()
        good = self._decodable_prefix_len(raw)
        if good == len(raw):
            return 0
        torn = len(raw) - good
        logger.warning(
            "WAL %s: torn tail repaired at open — truncated %d undecodable "
            "byte(s) after %d good byte(s) (crash mid-append; records past "
            "the tear were never durable)", path, torn, good)
        os.truncate(path, good)
        return torn

    # -- writing -----------------------------------------------------------

    def _write_record(self, payload: bytes, sync: bool) -> None:
        if len(payload) > MAX_MSG_SIZE_BYTES:
            raise ValueError(f"msg is too big: {len(payload)} bytes, max: {MAX_MSG_SIZE_BYTES}")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        frame = struct.pack(">II", crc, len(payload)) + payload
        # torn-write seam at the byte-emit point: a fired site emits a
        # strictly partial frame (seeded prefix + optional garbage), the
        # on-disk shape a crash mid-append leaves — repair-on-open and
        # CRC-bounded replay are exercised against real partial data
        self._f.write(faults.tear("wal.torn_write", frame))
        self._records_since_sync += 1
        if self._group_depth:
            # group commit: the batch's single flush/fsync happens at the
            # group() exit; record bytes are already in the file buffer in
            # write order, so replay framing is identical either way
            if self._group_records:
                # >=1 record of this batch appended, flush still pending —
                # the mid-group-commit durability boundary
                fail_point("wal.mid_group_commit")
            self._group_records += 1
            self._group_sync = self._group_sync or sync
            return
        self._f.flush()
        if sync:
            self._fsync()
        self._maybe_rotate()

    def _fsync(self) -> None:
        n = self._records_since_sync
        # pre/post-fsync durability boundaries (crashmatrix): before, the
        # records are appended+flushed but their durability is unclaimed;
        # after, they are durable and nothing has acted on them yet
        fail_point("wal.before_fsync")
        with tracer.span("wal_fsync", n_records=n):
            t0 = time.perf_counter()
            try:
                faults.inject("wal.fsync", _injected_eio)
                os.fsync(self._f.fileno())
            except OSError as e:
                self._on_fsync_error(e)
            dt = time.perf_counter() - t0
        fail_point("wal.after_fsync")
        self._last_sync_t = time.monotonic()
        self._records_since_sync = 0
        m = self.metrics
        if m is not None:
            m.wal_fsyncs_total.inc()
            if n:  # flush_and_sync() with an already-durable tail observes
                # no batch — only real record batches feed the histogram
                m.wal_records_per_fsync.observe(n)
            m.wal_fsync_seconds.observe(dt)

    def _on_fsync_error(self, e: OSError) -> None:
        """Fatal by default: a record whose fsync failed must never be
        treated as durable, and fsync retry semantics are untrustworthy
        (fsyncgate) — so crash and let restart replay from disk."""
        m = self.metrics
        if m is not None:
            m.wal_fsync_errors_total.inc()
        logger.critical(
            "WAL fsync failed (%s): %d record(s) of unknown durability; "
            "%s per fsync_error_policy", e, self._records_since_sync,
            "exiting" if self.fsync_error_policy == "exit" else "raising")
        if self.fsync_error_policy == "raise":
            raise FsyncError(f"WAL fsync failed: {e}") from e
        os._exit(FSYNC_EXIT_CODE)

    @contextlib.contextmanager
    def group(self):
        """Group commit: records written inside are appended immediately but
        their flush/fsync is deferred to the context exit — ONE fsync when
        any record in the batch wants durability (own messages), else only
        when ``sync_deadline_s`` has passed since the last sync. Nested
        groups collapse into the outermost. The batch is committed even
        when the body raises: the records are already appended, and a torn
        tail is reconciled by CRC-bounded replay exactly like a torn single
        record. Exception: a simulated process death (KilledAtFailPoint —
        the crashmatrix in-proc kill) commits NOTHING on the way out — a
        dead process flushes no batch, and committing here would make the
        mid-group-commit durability boundary vacuously durable."""
        if self._group_depth:
            yield self
            return
        self._group_depth = 1
        self._group_records = 0
        self._group_sync = False
        try:
            yield self
        except KilledAtFailPoint:
            self._group_depth = 0
            raise
        finally:
            if self._group_depth:
                self._group_depth = 0
                if self._group_records:
                    self._f.flush()
                    if self._group_sync or (time.monotonic() - self._last_sync_t
                                            >= self.sync_deadline_s):
                        self._fsync()
                    self._maybe_rotate()

    def _maybe_rotate(self) -> None:
        if self._f.tell() > self._head_size_limit:
            # flushed-but-unsynced records must not rotate away: after the
            # rename, fsyncs hit the NEW fd only, so the deadline lag bound
            # (and the records_per_fsync accounting) would silently exclude
            # them. Rotation is per ~10MB — one extra fsync is noise.
            if self._records_since_sync:
                self._fsync()
            self._f.close()
            idx = 0
            while os.path.exists(f"{self.path}.{idx}"):
                idx += 1
            os.rename(self.path, f"{self.path}.{idx}")
            self._f = open(self.path, "ab")

    def _envelope(self, type_: str, data: dict, time_ns: int) -> bytes:
        return json.dumps({"time_ns": time_ns, "type": type_, "data": data},
                          separators=(",", ":")).encode()

    def write(self, type_: str, data: dict, time_ns: int = 0) -> None:
        self._write_record(self._envelope(type_, data, time_ns), sync=False)

    def write_sync(self, type_: str, data: dict, time_ns: int = 0) -> None:
        self._write_record(self._envelope(type_, data, time_ns), sync=True)

    def write_msg_info(self, msg, peer_id: str, time_ns: int, internal: bool) -> None:
        """msgInfo records; fsync for our own messages (state.go:754,763)."""
        type_, data = _encode_msg(msg, peer_id)
        if internal:
            self.write_sync(type_, data, time_ns)
        else:
            self.write(type_, data, time_ns)

    def write_timeout(self, ti: TimeoutInfo, time_ns: int) -> None:
        self.write("timeout", {"duration_s": ti.duration_s, "height": ti.height,
                               "round": ti.round, "step": int(ti.step)}, time_ns)

    def write_end_height(self, height: int, time_ns: int) -> None:
        self.write_sync("end_height", {"height": height}, time_ns)

    def write_round_step(self, height: int, round_: int, step: int, time_ns: int) -> None:
        self.write("round_step", {"height": height, "round": round_, "step": step}, time_ns)

    def flush_and_sync(self) -> None:
        self._f.flush()
        self._fsync()

    def close(self) -> None:
        try:
            self._f.flush()
            self._f.close()
        except ValueError:
            pass

    # -- reading -----------------------------------------------------------

    def _all_paths(self) -> List[str]:
        """Rotated files oldest-first, then the head."""
        idx = 0
        out = []
        while os.path.exists(f"{self.path}.{idx}"):
            out.append(f"{self.path}.{idx}")
            idx += 1
        if os.path.exists(self.path):
            out.append(self.path)
        return out

    def iter_messages(self) -> Iterator[WALMessage]:
        """All decodable messages; stops cleanly at a torn/corrupt tail
        (reference wal decoder DataCorruptionError tolerance)."""
        for path in self._all_paths():
            with open(path, "rb") as f:
                raw = f.read()
            pos = 0
            while pos + 8 <= len(raw):
                crc, ln = struct.unpack_from(">II", raw, pos)
                if ln > MAX_MSG_SIZE_BYTES or pos + 8 + ln > len(raw):
                    return  # torn write at tail
                payload = raw[pos + 8:pos + 8 + ln]
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    return  # corruption: stop replay here
                try:
                    d = json.loads(payload.decode())
                except (ValueError, UnicodeDecodeError):
                    return
                yield WALMessage(d.get("time_ns", 0), d["type"], d.get("data", {}))
                pos += 8 + ln

    def search_for_end_height(self, height: int) -> bool:
        """True if #ENDHEIGHT for `height` exists (wal.go:231) — meaning the
        block at `height` was fully committed and WAL replay should start
        after that record."""
        for m in self.iter_messages():
            if m.type == "end_height" and m.data.get("height") == height:
                return True
        return False

    def messages_after_end_height(self, height: int) -> List[WALMessage]:
        """Messages following the #ENDHEIGHT record for `height`."""
        out: List[WALMessage] = []
        found = False
        for m in self.iter_messages():
            if found:
                out.append(m)
            elif m.type == "end_height" and m.data.get("height") == height:
                found = True
        return out


class NilWAL(WAL):
    """No-op WAL for tests (consensus/wal.go:421 nilWAL)."""

    def __init__(self):  # noqa: super-init-not-called
        pass

    def _write_record(self, payload: bytes, sync: bool) -> None:
        pass

    def write(self, *a, **k) -> None:
        pass

    def write_sync(self, *a, **k) -> None:
        pass

    def write_msg_info(self, *a, **k) -> None:
        pass

    def write_timeout(self, *a, **k) -> None:
        pass

    def write_end_height(self, *a, **k) -> None:
        pass

    def write_round_step(self, *a, **k) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def iter_messages(self):
        return iter(())
