"""Consensus write-ahead log (reference consensus/wal.go:58).

Every consensus input is logged before it acts on the state machine; own
(internal) messages are fsynced. Framing mirrors the reference encoder
(wal.go:288): crc32(payload) u32 BE || length u32 BE || payload. Payload is a
JSON envelope {time_ns, type, data} — msg types: "vote", "proposal",
"block_part", "timeout", "end_height", "round_step" (EventDataRoundStep).
Size-rotated like libs/autofile.Group.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote

MAX_MSG_SIZE_BYTES = 1024 * 1024  # 1MB (wal.go maxMsgSizeBytes)
DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # autofile group head rotation
DEFAULT_GROUP_LIMIT = 60 * 1024 * 1024


@dataclass
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int  # RoundStep value


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class WALMessage:
    time_ns: int
    type: str
    data: dict


def _encode_vote(v: Vote) -> dict:
    return {"vote": v.encode().hex()}


def _encode_msg(msg, peer_id: str) -> Tuple[str, dict]:
    from .state import BlockPartMessage, ProposalMessage, VoteMessage

    if isinstance(msg, VoteMessage):
        return "vote", {"vote": msg.vote.encode().hex(), "peer": peer_id}
    if isinstance(msg, ProposalMessage):
        return "proposal", {"proposal": msg.proposal.encode().hex(), "peer": peer_id}
    if isinstance(msg, BlockPartMessage):
        return "block_part", {"height": msg.height, "round": msg.round,
                              "part": msg.part.encode().hex(), "peer": peer_id}
    raise ValueError(f"unsupported WAL message {type(msg)}")


class WAL:
    def __init__(self, path: str, head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT):
        self.path = path
        self._head_size_limit = head_size_limit
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        # fresh WAL: write #ENDHEIGHT 0 so height-1 catchup replay has its
        # start marker (reference consensus/wal.go BaseWAL.OnStart)
        if self._f.tell() == 0 and not os.path.exists(f"{path}.0"):
            self.write_sync("end_height", {"height": 0})

    # -- writing -----------------------------------------------------------

    def _write_record(self, payload: bytes, sync: bool) -> None:
        if len(payload) > MAX_MSG_SIZE_BYTES:
            raise ValueError(f"msg is too big: {len(payload)} bytes, max: {MAX_MSG_SIZE_BYTES}")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(struct.pack(">II", crc, len(payload)) + payload)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        self._maybe_rotate()

    def _maybe_rotate(self) -> None:
        if self._f.tell() > self._head_size_limit:
            self._f.close()
            idx = 0
            while os.path.exists(f"{self.path}.{idx}"):
                idx += 1
            os.rename(self.path, f"{self.path}.{idx}")
            self._f = open(self.path, "ab")

    def _envelope(self, type_: str, data: dict, time_ns: int) -> bytes:
        return json.dumps({"time_ns": time_ns, "type": type_, "data": data},
                          separators=(",", ":")).encode()

    def write(self, type_: str, data: dict, time_ns: int = 0) -> None:
        self._write_record(self._envelope(type_, data, time_ns), sync=False)

    def write_sync(self, type_: str, data: dict, time_ns: int = 0) -> None:
        self._write_record(self._envelope(type_, data, time_ns), sync=True)

    def write_msg_info(self, msg, peer_id: str, time_ns: int, internal: bool) -> None:
        """msgInfo records; fsync for our own messages (state.go:754,763)."""
        type_, data = _encode_msg(msg, peer_id)
        if internal:
            self.write_sync(type_, data, time_ns)
        else:
            self.write(type_, data, time_ns)

    def write_timeout(self, ti: TimeoutInfo, time_ns: int) -> None:
        self.write("timeout", {"duration_s": ti.duration_s, "height": ti.height,
                               "round": ti.round, "step": int(ti.step)}, time_ns)

    def write_end_height(self, height: int, time_ns: int) -> None:
        self.write_sync("end_height", {"height": height}, time_ns)

    def write_round_step(self, height: int, round_: int, step: int, time_ns: int) -> None:
        self.write("round_step", {"height": height, "round": round_, "step": step}, time_ns)

    def flush_and_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
            self._f.close()
        except ValueError:
            pass

    # -- reading -----------------------------------------------------------

    def _all_paths(self) -> List[str]:
        """Rotated files oldest-first, then the head."""
        idx = 0
        out = []
        while os.path.exists(f"{self.path}.{idx}"):
            out.append(f"{self.path}.{idx}")
            idx += 1
        if os.path.exists(self.path):
            out.append(self.path)
        return out

    def iter_messages(self) -> Iterator[WALMessage]:
        """All decodable messages; stops cleanly at a torn/corrupt tail
        (reference wal decoder DataCorruptionError tolerance)."""
        for path in self._all_paths():
            with open(path, "rb") as f:
                raw = f.read()
            pos = 0
            while pos + 8 <= len(raw):
                crc, ln = struct.unpack_from(">II", raw, pos)
                if ln > MAX_MSG_SIZE_BYTES or pos + 8 + ln > len(raw):
                    return  # torn write at tail
                payload = raw[pos + 8:pos + 8 + ln]
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    return  # corruption: stop replay here
                try:
                    d = json.loads(payload.decode())
                except (ValueError, UnicodeDecodeError):
                    return
                yield WALMessage(d.get("time_ns", 0), d["type"], d.get("data", {}))
                pos += 8 + ln

    def search_for_end_height(self, height: int) -> bool:
        """True if #ENDHEIGHT for `height` exists (wal.go:231) — meaning the
        block at `height` was fully committed and WAL replay should start
        after that record."""
        for m in self.iter_messages():
            if m.type == "end_height" and m.data.get("height") == height:
                return True
        return False

    def messages_after_end_height(self, height: int) -> List[WALMessage]:
        """Messages following the #ENDHEIGHT record for `height`."""
        out: List[WALMessage] = []
        found = False
        for m in self.iter_messages():
            if found:
                out.append(m)
            elif m.type == "end_height" and m.data.get("height") == height:
                found = True
        return out


class NilWAL(WAL):
    """No-op WAL for tests (consensus/wal.go:421 nilWAL)."""

    def __init__(self):  # noqa: super-init-not-called
        pass

    def _write_record(self, payload: bytes, sync: bool) -> None:
        pass

    def write(self, *a, **k) -> None:
        pass

    def write_sync(self, *a, **k) -> None:
        pass

    def write_msg_info(self, *a, **k) -> None:
        pass

    def write_timeout(self, *a, **k) -> None:
        pass

    def write_end_height(self, *a, **k) -> None:
        pass

    def write_round_step(self, *a, **k) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def iter_messages(self):
        return iter(())
