"""Round bookkeeping: RoundStep, RoundState, HeightVoteSet
(reference consensus/types/round_state.go:67, height_vote_set.go:41).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional, Set, Tuple

from ..types import ValidatorSet, VoteSet
from ..types.basic import BlockID, SignedMsgType
from ..types.block import Block, Commit
from ..types.errors import ErrVoteConflictingVotes
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.vote import Vote


class RoundStep(IntEnum):
    """(round_state.go:20-32)"""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8

    def short_name(self) -> str:
        return {
            1: "NewHeight", 2: "NewRound", 3: "Propose", 4: "Prevote",
            5: "PrevoteWait", 6: "Precommit", 7: "PrecommitWait", 8: "Commit",
        }[int(self)]


@dataclass
class RoundState:
    """The consensus core's mutable view of one height (round_state.go:67)."""

    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional["HeightVoteSet"] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False


class HeightVoteSet:
    """One prevote + precommit VoteSet per round; tracks peer maj23 claims
    (consensus/types/height_vote_set.go:41). Keeps round 0..round+1 live to
    allow round skipping.
    """

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet,
                 verifier=None):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.verifier = verifier
        self.round = 0
        self._round_vote_sets: Dict[int, Tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: Dict[str, List[int]] = {}
        self._add_round(0)
        self._add_round(1)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        prevotes = VoteSet(self.chain_id, self.height, round_,
                           SignedMsgType.PREVOTE, self.val_set,
                           verifier=self.verifier)
        precommits = VoteSet(self.chain_id, self.height, round_,
                             SignedMsgType.PRECOMMIT, self.val_set,
                             verifier=self.verifier)
        self._round_vote_sets[round_] = (prevotes, precommits)

    def set_round(self, round_: int) -> None:
        """Track round 0..round (height_vote_set.go:104 SetRound)."""
        new_round = self.round - 1 if self.round > 0 else 0
        for r in range(new_round, round_ + 1):
            self._add_round(r)
        self.round = round_

    def add_vote(self, vote: Vote, peer_id: str) -> bool:
        """(height_vote_set.go:117) — peer catchup rounds are rate-limited to 2."""
        if not self._is_vote_type_valid(vote.type):
            return False
        vote_set = self._get_vote_set(vote.round, vote.type)
        if vote_set is None:
            rndz = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rndz) < 2:
                self._add_round(vote.round)
                vote_set = self._get_vote_set(vote.round, vote.type)
                rndz.append(vote.round)
            else:
                raise GotVoteFromUnwantedRound(
                    f"peer has sent a vote that does not match our round for more "
                    f"than one round; peer={peer_id} height={vote.height} round={vote.round}")
        return vote_set.add_vote(vote)

    @staticmethod
    def _is_vote_type_valid(t: SignedMsgType) -> bool:
        return t in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, SignedMsgType.PREVOTE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, SignedMsgType.PRECOMMIT)

    def _get_vote_set(self, round_: int, t: SignedMsgType) -> Optional[VoteSet]:
        pair = self._round_vote_sets.get(round_)
        if pair is None:
            return None
        return pair[0] if t == SignedMsgType.PREVOTE else pair[1]

    def pol_info(self) -> Tuple[int, Optional[BlockID]]:
        """Last round with a prevote polka, searched descending
        (height_vote_set.go:185 POLInfo)."""
        for r in range(self.round, -1, -1):
            rvs = self.prevotes(r)
            if rvs is not None:
                block_id, ok = rvs.two_thirds_majority()
                if ok:
                    return r, block_id
        return -1, None

    def set_peer_maj23(self, round_: int, vote_type: SignedMsgType,
                       peer_id: str, block_id: BlockID) -> None:
        if not self._is_vote_type_valid(vote_type):
            return
        self._add_round(round_)
        vote_set = self._get_vote_set(round_, vote_type)
        vote_set.set_peer_maj23(peer_id, block_id)


class GotVoteFromUnwantedRound(Exception):
    pass


def commit_to_vote_set(chain_id: str, commit: Commit,
                       val_set: ValidatorSet) -> "VoteSet | AggregatedLastCommit":
    """Reconstruct the precommit VoteSet backing a Commit
    (reference types/vote_set.go CommitToVoteSet in vote_set.go / block.go).
    An AggregatedCommit cannot be exploded back into votes (the per-validator
    signatures are gone) — it is wrapped in the read-only adapter instead."""
    if hasattr(commit, "agg_sig"):
        val_set.verify_commit_light(chain_id, commit.block_id, commit.height,
                                    commit)
        return AggregatedLastCommit(chain_id, commit, val_set)
    vote_set = VoteSet(chain_id, commit.height, commit.round,
                       SignedMsgType.PRECOMMIT, val_set)
    for idx, cs in enumerate(commit.signatures):
        if cs.absent():
            continue
        added = vote_set.add_vote(commit.get_vote(idx))
        if not added:
            raise ValueError(f"failed to reconstruct LastCommit: vote {idx} not added")
    return vote_set


class AggregatedLastCommit:
    """Read-only stand-in for rs.last_commit after a restart on an
    aggregated chain.  The stored AggregatedCommit has no per-validator
    votes to re-add or gossip, so this adapter answers the VoteSet surface
    the consensus core and reactor actually touch: the majority is already
    proven (verified in commit_to_vote_set), make_commit returns the commit
    verbatim for the next proposal, late precommits are dropped, and the
    vote-gossip bit array is empty so nothing tries to fetch votes that no
    longer exist (peers one height back catch up via block sync)."""

    def __init__(self, chain_id: str, commit, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = commit.height
        self.round = commit.round
        self.signed_msg_type = SignedMsgType.PRECOMMIT
        self.val_set = val_set
        self._commit = commit

    def size(self) -> int:
        return self._commit.size()

    def has_two_thirds_majority(self) -> bool:
        return True

    def two_thirds_majority(self):
        return self._commit.block_id, True

    def make_commit(self):
        return self._commit

    def add_vote(self, vote) -> bool:
        return False  # nothing to accumulate into

    def list_votes(self):
        # no per-validator votes survive aggregation — the subjective
        # commit-time window check then falls back to its clock bound
        return []

    def has_all(self) -> bool:
        return self._commit.signers.is_full()

    def bit_array(self):
        from ..libs.bits import BitArray

        return BitArray(self._commit.size())

    def get_by_index(self, idx: int):
        return None
