"""Consensus timing/behaviour config (reference config/config.go:917 ConsensusConfig).

Two timeout modes:

* ``spec`` (default) — the reference's fixed linear-in-round schedule:
  ``timeout_X + timeout_X_delta * round``. Byte-identical to the config
  that existed before adaptive mode; nothing consults the controller.
* ``adaptive`` (opt-in) — :class:`AdaptiveTimeouts` keeps one EWMA per
  timeout class over the stage timeline's sealed per-height durations
  (proposal arrival, proposal→prevote-quorum, prevote→precommit-quorum)
  and sets each round-0 baseline to ``clamp(headroom * ewma, spec,
  spec * adaptive_max_scale)``; the per-round delta escalation is
  unchanged. The controller is a pure fold over the observation stream —
  same sealed durations in the same order → same timeouts — so seeded
  degraded-network runs stay replayable. Under a WAN profile the floor
  clamp means adaptive can only *raise* timeouts toward observed reality
  (fewer spurious round escalations), never starve below spec.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class ConsensusConfig:
    # all times in seconds (float); defaults from config/config.go:996-1010
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    double_sign_check_height: int = 0
    wal_file: str = ""
    # gossip sleeps (reactor). With event wakeups on, the sleep is only the
    # FALLBACK cap on how stale a gossip iteration can go without a signal —
    # state transitions, new parts, and new votes wake the routines directly.
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    peer_gossip_event_wakeups: bool = True
    # WAL group commit: the receive loop drains up to max_batch queued
    # messages, logs them all, and fsyncs ONCE when any is our own —
    # records and ordering identical to per-record sync, fewer disk syncs.
    wal_group_commit: bool = True
    wal_group_commit_max_batch: int = 128
    # fsync deadline for grouped batches with only peer records (which the
    # reference never syncs at all; this bounds the async tail's lag)
    wal_sync_deadline: float = 0.05
    # self-healing gossip: a peer silent for this long AND behind our
    # height gets its delivery bitmaps cleared so catchup re-sends
    # (PeerState.refresh_if_stalled; the behind-gate is in the reactor).
    # Gossip marks votes/parts delivered ON SEND — sound over reliable
    # TCP, but a lossy/blackholed link silently eats sends and the
    # bookkeeping then wedges the link forever. Quiet for healthy nets:
    # a peer at our height triggers nothing. 0 disables.
    gossip_stall_refresh_s: float = 10.0
    # stall watchdog: no committed-height advance for this many seconds →
    # consensus_stalled_total + a debugdump bundle (consensus/watchdog.py).
    # 0 disables (default: a net configured to idle between txs would
    # false-positive); e2e/chaos nets enable it.
    stall_watchdog_s: float = 0.0
    # Aggregated commits: the commit timestamp is covered by NO signature
    # (precommits sign zero-timestamp bytes), so before prevoting a proposal
    # each validator subjectively bounds the proposed last-commit timestamp
    # within this drift of its own recorded precommit times / local clock
    # (ConsensusState._check_aggregated_commit_time). 0 disables the check.
    agg_commit_time_drift_s: float = 10.0
    # round-timeout mode: "spec" keeps the fixed linear-in-round schedule
    # above; "adaptive" folds the stage timeline's observed latencies into
    # per-class EWMAs (see AdaptiveTimeouts) clamped to
    # [spec, spec * adaptive_max_scale]
    timeout_mode: str = "spec"
    # EWMA gain per sealed height (weight of the newest observation)
    adaptive_gain: float = 0.25
    # baseline = headroom * ewma before clamping: the slack multiple over
    # the observed latency a round must fit in before escalating
    adaptive_headroom: float = 2.0
    # clamp ceiling as a multiple of the spec timeout (spec_max)
    adaptive_max_scale: float = 5.0

    def propose(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit_time_ns(self, t_ns: int) -> int:
        return t_ns + int(self.timeout_commit * 1e9)

    def wait_for_txs(self) -> bool:
        return not self.create_empty_blocks or self.create_empty_blocks_interval > 0

    def validate_timeout_mode(self) -> None:
        if self.timeout_mode not in ("spec", "adaptive"):
            raise ValueError(
                f"unknown timeout_mode {self.timeout_mode!r}; "
                f'known: ("spec", "adaptive")')


class AdaptiveTimeouts:
    """Deterministic EWMA controller for adaptive round timeouts.

    One EWMA per timeout class, fed from the stage timeline's sealed
    per-height duration dicts (``StageTimeline._seal``):

    * ``propose``   ← time to ``proposal_received`` (height open → proposal
      accepted by the state machine — what timeout_propose waits on)
    * ``prevote``   ← ``prevote_sent`` + ``prevote_quorum`` deltas
      (proposal → 2/3+ prevotes — what timeout_prevote waits on)
    * ``precommit`` ← ``precommit_sent`` + ``precommit_quorum`` deltas
      (polka → 2/3+ precommits — what timeout_precommit waits on)

    ``timeout(kind, round)`` returns ``clamp(headroom * ewma, spec,
    spec * max_scale) + spec_delta * round`` — the round escalation delta
    is untouched, only the round-0 baseline adapts. Pure fold: state is
    three floats, updated only in :meth:`observe`, so two nodes (or two
    runs) fed the same observation stream compute bit-identical timeouts.
    Before the first observation every class sits at its spec floor —
    adaptive mode starts exactly where spec mode is.
    """

    _CLASSES = ("propose", "prevote", "precommit")

    def __init__(self, config: ConsensusConfig):
        self.config = config
        self.ewma: Dict[str, Optional[float]] = {k: None for k in self._CLASSES}
        self.heights_observed = 0

    def observe(self, durations: Dict[str, float]) -> None:
        """Fold one sealed height's stage durations into the EWMAs.
        Missing stages (non-validator seals, fast-sync gaps) leave the
        affected class untouched rather than feeding it a zero."""
        g = self.config.adaptive_gain
        obs = {
            "propose": durations.get("proposal_received"),
            "prevote": self._span(durations, "prevote_sent", "prevote_quorum"),
            "precommit": self._span(durations, "precommit_sent",
                                    "precommit_quorum"),
        }
        for kind, x in obs.items():
            if x is None:
                continue
            prev = self.ewma[kind]
            self.ewma[kind] = x if prev is None else prev + g * (x - prev)
        self.heights_observed += 1

    @staticmethod
    def _span(durations: Dict[str, float], *stages: str) -> Optional[float]:
        got = [durations[s] for s in stages if s in durations]
        return sum(got) if got else None

    def timeout(self, kind: str, round_: int) -> float:
        cfg = self.config
        spec = getattr(cfg, f"timeout_{kind}")
        delta = getattr(cfg, f"timeout_{kind}_delta")
        ewma = self.ewma[kind]
        base = spec
        if ewma is not None:
            base = min(max(cfg.adaptive_headroom * ewma, spec),
                       spec * cfg.adaptive_max_scale)
        return base + delta * round_

    def snapshot(self) -> Dict[str, float]:
        """JSON-safe controller state (debugdump / RPC / tests)."""
        out = {"heights_observed": self.heights_observed}
        for kind in self._CLASSES:
            e = self.ewma[kind]
            out[f"ewma_{kind}"] = round(e, 6) if e is not None else None
            out[f"timeout_{kind}_r0"] = round(self.timeout(kind, 0), 6)
        return out


def test_consensus_config() -> ConsensusConfig:
    """Fast timeouts for in-proc tests (reference config TestConsensusConfig)."""
    return ConsensusConfig(  # noqa
        timeout_propose=0.08,
        timeout_propose_delta=0.05,
        timeout_prevote=0.01,
        timeout_prevote_delta=0.01,
        timeout_precommit=0.01,
        timeout_precommit_delta=0.01,
        timeout_commit=0.01,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration=0.005,
        peer_query_maj23_sleep_duration=0.25,
    )


test_consensus_config.__test__ = False  # not a pytest test despite the name
