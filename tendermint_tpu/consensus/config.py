"""Consensus timing/behaviour config (reference config/config.go:917 ConsensusConfig)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ConsensusConfig:
    # all times in seconds (float); defaults from config/config.go:996-1010
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    double_sign_check_height: int = 0
    wal_file: str = ""
    # gossip sleeps (reactor). With event wakeups on, the sleep is only the
    # FALLBACK cap on how stale a gossip iteration can go without a signal —
    # state transitions, new parts, and new votes wake the routines directly.
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    peer_gossip_event_wakeups: bool = True
    # WAL group commit: the receive loop drains up to max_batch queued
    # messages, logs them all, and fsyncs ONCE when any is our own —
    # records and ordering identical to per-record sync, fewer disk syncs.
    wal_group_commit: bool = True
    wal_group_commit_max_batch: int = 128
    # fsync deadline for grouped batches with only peer records (which the
    # reference never syncs at all; this bounds the async tail's lag)
    wal_sync_deadline: float = 0.05
    # self-healing gossip: a peer silent for this long AND behind our
    # height gets its delivery bitmaps cleared so catchup re-sends
    # (PeerState.refresh_if_stalled; the behind-gate is in the reactor).
    # Gossip marks votes/parts delivered ON SEND — sound over reliable
    # TCP, but a lossy/blackholed link silently eats sends and the
    # bookkeeping then wedges the link forever. Quiet for healthy nets:
    # a peer at our height triggers nothing. 0 disables.
    gossip_stall_refresh_s: float = 10.0
    # stall watchdog: no committed-height advance for this many seconds →
    # consensus_stalled_total + a debugdump bundle (consensus/watchdog.py).
    # 0 disables (default: a net configured to idle between txs would
    # false-positive); e2e/chaos nets enable it.
    stall_watchdog_s: float = 0.0
    # Aggregated commits: the commit timestamp is covered by NO signature
    # (precommits sign zero-timestamp bytes), so before prevoting a proposal
    # each validator subjectively bounds the proposed last-commit timestamp
    # within this drift of its own recorded precommit times / local clock
    # (ConsensusState._check_aggregated_commit_time). 0 disables the check.
    agg_commit_time_drift_s: float = 10.0

    def propose(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit_time_ns(self, t_ns: int) -> int:
        return t_ns + int(self.timeout_commit * 1e9)

    def wait_for_txs(self) -> bool:
        return not self.create_empty_blocks or self.create_empty_blocks_interval > 0


def test_consensus_config() -> ConsensusConfig:
    """Fast timeouts for in-proc tests (reference config TestConsensusConfig)."""
    return ConsensusConfig(  # noqa
        timeout_propose=0.08,
        timeout_propose_delta=0.05,
        timeout_prevote=0.01,
        timeout_prevote_delta=0.01,
        timeout_precommit=0.01,
        timeout_precommit_delta=0.01,
        timeout_commit=0.01,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration=0.005,
        peer_query_maj23_sleep_duration=0.25,
    )


test_consensus_config.__test__ = False  # not a pytest test despite the name
