"""The consensus state machine (reference consensus/state.go:78).

A single async task (`receive_routine`) serializes every input — peer
messages, our own signed messages, timeouts — exactly like the reference's
single-goroutine receiveRoutine (state.go:707). State transitions happen only
inside it. WAL-before-act discipline: every message is logged (fsync for our
own) before it mutates the round state.

All enter* transitions are synchronous functions: one message is processed
atomically from queue-pop to quiescence, which is the asyncio equivalent of
the reference's per-message mutex hold.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from ..libs.trace import tracer
from ..state import BlockExecutor, State
from ..state.store import StateStore
from ..store import BlockStore
from ..types import PrivValidator, ValidatorSet
from ..types.basic import BlockID, PartSetHeader, SignedMsgType
from ..types.block import Block, Commit
from ..types.errors import ErrVoteConflictingVotes
from ..types.event_bus import (
    EventBus,
    EventDataCompleteProposal,
    EventDataNewRound,
    EventDataRoundState,
)
from ..types.part_set import Part, PartSet
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..types.vote_set import VoteSetError
from .config import ConsensusConfig
from .round_state import (
    HeightVoteSet,
    RoundState,
    RoundStep,
    commit_to_vote_set,
)
from .wal import WAL, NilWAL, TimeoutInfo

logger = logging.getLogger("tmtpu.consensus")


# --- messages (consensus/msgs.go domain side) ------------------------------

@dataclass
class ProposalMessage:
    proposal: Proposal


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass
class VoteMessage:
    vote: Vote


@dataclass
class _MsgInfo:
    msg: object
    peer_id: str  # "" == internal


def now_ns() -> int:
    return time.time_ns()


def check_aggregated_commit_time(commit, seen_ts_ns, now_ns_, drift_ns) -> None:
    """Window check behind ConsensusState._check_aggregated_commit_time
    (split out so it is testable without a live state machine).

    `seen_ts_ns` are the precommit timestamps THIS node recorded for the
    commit's height from validators inside the signer bitmap; possibly a
    subset of what the proposer aggregated over, so the window keeps
    drift-sized slack on both sides.  Raises ValueError on a timestamp
    outside [min(seen)-drift, max(seen)+drift] or more than drift ahead of
    the local clock."""
    ts = commit.timestamp_ns
    if ts > now_ns_ + drift_ns:
        raise ValueError(
            f"aggregated commit timestamp {ts} is more than "
            f"{drift_ns / 1e9:g}s ahead of local time {now_ns_}")
    if seen_ts_ns:
        lo, hi = min(seen_ts_ns) - drift_ns, max(seen_ts_ns) + drift_ns
        if not lo <= ts <= hi:
            raise ValueError(
                f"aggregated commit timestamp {ts} outside the window "
                f"[{lo}, {hi}] of locally recorded precommit times")


class ConsensusState:
    def __init__(self, config: ConsensusConfig, state: State,
                 block_exec: BlockExecutor, block_store: BlockStore,
                 tx_notifier=None, evpool=None,
                 wal: Optional[WAL] = None):
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.evpool = evpool if evpool is not None else block_exec.evpool
        self.wal: WAL = wal or NilWAL()

        self.rs = RoundState()
        self.state: State = State()  # set via update_to_state

        self.priv_validator: Optional[PrivValidator] = None
        self.priv_validator_pub_key = None

        self.event_bus: Optional[EventBus] = None
        # internal event switch (reference evsw): reactor hooks
        self.new_round_step_listeners: List[Callable[[RoundState], None]] = []
        self.valid_block_listeners: List[Callable[[RoundState], None]] = []
        self.vote_listeners: List[Callable[[Vote], None]] = []
        # fired when new gossip-able proposal data lands (proposal accepted /
        # block part added) — the reactor wakes per-peer data routines here
        # instead of them polling on peer_gossip_sleep_duration
        self.proposal_data_listeners: List[Callable[[], None]] = []
        # maverick hook: votes pushed STRAIGHT to peers, bypassing our own
        # VoteSet (which rightly rejects equivocations)
        self.equivocation_listeners: List[Callable[[Vote], None]] = []

        # HOT LOOP #1 seam: gossiped-vote signature checks go through a
        # micro-batching verifier (crypto/vote_batcher.py). The reactor
        # pre-verifies concurrently in batches; the single-writer loop then
        # consumes cached verdicts via VoteSet.add_vote.
        from ..crypto.vote_batcher import BatchVoteVerifier
        self.vote_verifier = BatchVoteVerifier()
        self.metrics = None  # ConsensusMetrics, wired by the node
        # per-height stage timeline (consensus/timeline.py): wall-clock
        # marks at each stage of every height, sealed at commit into
        # stage_seconds histograms + height-tagged trace spans + a bounded
        # ring served over RPC/debugdump. Always on — a mark is a couple of
        # clock reads and dict stores per stage per height.
        from .timeline import StageTimeline
        self.timeline = StageTimeline()
        # adaptive round timeouts (opt-in, config.timeout_mode): a pure
        # EWMA fold over the timeline's sealed per-height durations —
        # spec mode leaves self.adaptive None and every timeout lookup
        # byte-identical to the fixed schedule
        config.validate_timeout_mode()
        self.adaptive = None
        if config.timeout_mode == "adaptive":
            from .config import AdaptiveTimeouts
            self.adaptive = AdaptiveTimeouts(config)
            self.timeline.on_seal = self.adaptive.observe
        # seeded clock-skew plane (libs/faults.py "clock.skew"): this
        # node's deterministic wall-clock offset, threaded through the
        # consensus-visible timestamps via _now_ns; assigned when the priv
        # validator is wired (its address is the stable per-node identity)
        self.clock_skew_ns = 0
        # byzantine test hooks (the reference's maverick node,
        # test/maverick/consensus/misbehavior.go): height -> behavior name.
        # Supported: "double-prevote" (equivocate at prevote). Only MockPV
        # signers cooperate — FilePV's double-sign protection refuses.
        self.misbehaviors: dict = {}

        self._queue: "asyncio.Queue" = asyncio.Queue(maxsize=1000)
        self.wal.sync_deadline_s = config.wal_sync_deadline
        self._timeout_task: Optional[asyncio.Task] = None
        self._pending_timeout: Optional[TimeoutInfo] = None
        self._receive_task: Optional[asyncio.Task] = None
        self._stopped = False
        self.n_steps = 0
        self._replay_mode = False

        # reconstruct BEFORE update: updateToState requires rs.last_commit
        # when starting on an existing chain (reference state.go NewState)
        self.reconstruct_last_commit(state)
        self.update_to_state(state)

    # -- wiring ------------------------------------------------------------

    def set_priv_validator(self, pv: Optional[PrivValidator]) -> None:
        self.priv_validator = pv
        if pv is not None:
            self.priv_validator_pub_key = pv.get_pub_key()
            from ..libs.faults import faults
            if faults.armed("clock.skew"):
                ident = self.priv_validator_pub_key.address().hex()
                self.clock_skew_ns = faults.skew_ns("clock.skew", ident)

    def set_event_bus(self, bus: EventBus) -> None:
        self.event_bus = bus

    def _now_ns(self) -> int:
        """Wall clock as THIS node sees it: now_ns() plus the node's
        deterministic clock.skew offset. Only consensus-VISIBLE timestamps
        (votes, proposals, commit time) read the skewed clock — WAL
        records and timeout scheduling stay on the unskewed local clock,
        mirroring a real deployment where a skewed clock changes what a
        node claims, not how fast its timers run."""
        return now_ns() + self.clock_skew_ns

    def _round_timeout_s(self, kind: str, round_: int) -> float:
        """Round timeout per config.timeout_mode: the fixed spec schedule
        (``config.propose/prevote/precommit``) or the adaptive
        controller's clamped EWMA baseline plus the same per-round delta."""
        if self.adaptive is not None:
            return self.adaptive.timeout(kind, round_)
        return getattr(self.config, kind)(round_)

    def _note_round_advance(self, reason: str) -> None:
        """Degraded-network telemetry: count a round-escalation event
        (series tendermint_consensus_round_advances_total{reason})."""
        if self.metrics is not None:
            self.metrics.round_advances_total.labels(reason).inc()

    # -- external input (reactor → queues) ---------------------------------

    async def add_peer_msg(self, msg, peer_id: str) -> None:
        await self._queue.put(_MsgInfo(msg, peer_id))

    def send_internal(self, msg) -> None:
        """Internal messages must not be dropped (state.go sendInternalMessage)."""
        self._queue.put_nowait(_MsgInfo(msg, ""))

    async def set_proposal_and_block(self, proposal: Proposal, parts: PartSet,
                                     peer_id: str) -> None:
        """Test/replay helper mirroring the reference's blocking variant."""
        await self.add_peer_msg(ProposalMessage(proposal), peer_id)
        for i in range(parts.total):
            await self.add_peer_msg(
                BlockPartMessage(proposal.height, proposal.round, parts.get_part(i)),
                peer_id)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """(state.go:299 OnStart — minus WAL catchup, see replay.py)"""
        self._receive_task = asyncio.create_task(self.receive_routine(),
                                                 name=f"cs-receive-{id(self)}")
        self._schedule_round0()

    async def stop(self) -> None:
        self._stopped = True
        if self._timeout_task is not None:
            self._timeout_task.cancel()
        if self._receive_task is not None:
            self._receive_task.cancel()
            try:
                await self._receive_task
            except asyncio.CancelledError:
                pass
        self.wal.close()

    def _record_commit_metrics(self, block) -> None:
        """(consensus/metrics.go series recorded at commit)"""
        m = self.metrics
        m.height.set(block.header.height)
        m.rounds.set(self.rs.round)
        vals = self.rs.validators
        if vals is not None:
            m.validators.set(vals.size())
            m.validators_power.set(vals.total_voting_power())
        if block.last_commit is not None and self.rs.last_validators is not None:
            lvals = self.rs.last_validators
            missing = missing_power = 0
            our_addr = (self.priv_validator.get_pub_key().address()
                        if self.priv_validator is not None else None)
            aggregated = hasattr(block.last_commit, "agg_sig")
            for i in range(block.last_commit.size()):
                if aggregated:
                    absent = not block.last_commit.signers.get_index(i)
                else:
                    absent = block.last_commit.signatures[i].absent()
                _, val = lvals.get_by_index(i)
                if absent:
                    missing += 1
                    if val is not None:
                        missing_power += val.voting_power
                        if our_addr is not None and val.address == our_addr:
                            m.validator_missed_blocks.inc()
                elif (val is not None and our_addr is not None
                        and val.address == our_addr):
                    m.validator_last_signed_height.set(
                        block.header.height - 1)
            m.missing_validators.set(missing)
            m.missing_validators_power.set(missing_power)
        if vals is not None and self.priv_validator is not None:
            _, us = vals.get_by_address(
                self.priv_validator.get_pub_key().address())
            m.validator_power.set(us.voting_power if us is not None else 0)
        m.committed_height.set(block.header.height)
        m.latest_block_height.set(block.header.height)
        m.num_txs.set(len(block.data.txs))
        # block size from the part set already in hand — re-encoding a
        # potentially huge block inside the single-writer loop just for a
        # gauge would delay the next height
        parts = self.rs.proposal_block_parts
        if parts is not None:
            m.block_size_bytes.set(parts.byte_size)
        m.total_txs.inc(len(block.data.txs))
        # (reference state.go recordMetrics) byzantine gauges count the
        # EQUIVOCATING VALIDATORS, not evidence items: LightClientAttack
        # carries its validator list; DuplicateVote names one validator by
        # address, resolved against the current set for its power
        byz_power = 0
        byz_validators = set()
        for ev in block.evidence:
            lc_vals = getattr(ev, "byzantine_validators", None)
            if lc_vals:
                for v in lc_vals:
                    if v.address not in byz_validators:  # dedup across items
                        byz_validators.add(v.address)
                        byz_power += getattr(v, "voting_power", 0)
                continue
            vote_a = getattr(ev, "vote_a", None)
            if vote_a is not None:
                addr = vote_a.validator_address
                if addr not in byz_validators:
                    byz_validators.add(addr)
                    if vals is not None:
                        _, val = vals.get_by_address(addr)
                        if val is not None:
                            byz_power += val.voting_power
        m.byzantine_validators.set(len(byz_validators))
        m.byzantine_validators_power.set(byz_power)
        if self.state.last_block_time_ns:
            m.block_interval_seconds.observe(
                max(0.0, (block.header.time_ns - self.state.last_block_time_ns)
                    / 1e9))

    def _schedule_round0(self) -> None:
        sleep_s = max(0.0, (self.rs.start_time_ns - now_ns()) / 1e9)
        self._schedule_timeout(sleep_s, self.rs.height, 0, RoundStep.NEW_HEIGHT)

    # -- timeout ticker (consensus/ticker.go: one timeout at a time) -------

    def _schedule_timeout(self, duration_s: float, height: int, round_: int,
                          step: RoundStep) -> None:
        ti = TimeoutInfo(duration_s, height, round_, int(step))
        old = self._pending_timeout
        # ignore timeouts for an earlier-or-equal (H,R,S) than the last one
        # scheduled (ticker.go:94 timeoutRoutine) — a stray earlier-step
        # schedule must not cancel a later-step timeout (liveness hazard)
        if old is not None:
            if ti.height < old.height:
                return
            if ti.height == old.height:
                if ti.round < old.round:
                    return
                if ti.round == old.round and old.step > 0 and ti.step <= old.step:
                    return
        if self._timeout_task is not None:
            self._timeout_task.cancel()
        self._pending_timeout = ti
        self._timeout_task = asyncio.create_task(self._fire_timeout(ti))

    async def _fire_timeout(self, ti: TimeoutInfo) -> None:
        try:
            await asyncio.sleep(ti.duration_s)
        except asyncio.CancelledError:
            return
        await self._queue.put(ti)

    # -- the single-writer loop (state.go:707) -----------------------------

    async def receive_routine(self) -> None:
        grouped = self.config.wal_group_commit
        max_batch = (max(1, self.config.wal_group_commit_max_batch)
                     if grouped else 1)
        while not self._stopped:
            # queue.get() on a non-empty queue does not suspend; without this
            # yield a busy chain (internal msgs re-enqueue forever) starves
            # every other task and timer on the loop.
            await asyncio.sleep(0)
            item = await self._queue.get()
            batch = [item]
            while len(batch) < max_batch and not self._queue.empty():
                batch.append(self._queue.get_nowait())
            # phase 1 — WAL every record in the batch under ONE group commit
            # (a single fsync covers all own messages), BEFORE any of them
            # acts on the round state: an own message is always durable
            # before the transition that exposes it to gossip, exactly the
            # reference's per-record write-sync-then-handle guarantee with
            # the syncs coalesced. A record that fails to write drops its
            # message from phase 2 (as a failed write always skipped the
            # handle), without dropping the rest of the batch.
            loggable = []
            try:
                # with group commit disabled this is the exact legacy path:
                # batch size 1, no group() — own records fsync per record,
                # peer records are flushed but never fsynced
                ctx = (self.wal.group() if grouped
                       else contextlib.nullcontext())
                with tracer.span("wal_group", n=len(batch),
                                 height=self.rs.height), ctx:
                    for it in batch:
                        try:
                            if isinstance(it, TimeoutInfo):
                                self.wal.write_timeout(it, now_ns())
                            elif isinstance(it, _MsgInfo):
                                self.wal.write_msg_info(
                                    it.msg, it.peer_id, now_ns(),
                                    internal=it.peer_id == "")
                            loggable.append(it)
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            logger.exception(
                                "error writing consensus WAL record "
                                "(height=%d round=%d step=%s)",
                                self.rs.height, self.rs.round, self.rs.step)
            except asyncio.CancelledError:
                raise
            except Exception:
                # the group's deferred flush/fsync failed (disk full, EIO):
                # the batch's records may not be durable. Match the
                # per-record behavior — a failed sync skipped that message —
                # by dropping OWN messages from handling (their durability
                # rule would be violated) while peer messages, which were
                # never synced in the reference either, still proceed. The
                # loop itself must survive: it is an unsupervised task.
                logger.exception(
                    "consensus WAL group commit failed "
                    "(height=%d round=%d step=%s); dropping own messages "
                    "from this batch", self.rs.height, self.rs.round,
                    self.rs.step)
                loggable = [it for it in loggable
                            if not (isinstance(it, _MsgInfo)
                                    and it.peer_id == "")]
            # phase 2 — handle in arrival order. A commit inside the batch
            # writes its #ENDHEIGHT marker AFTER records phase 1 already
            # appended, and crash replay reads only messages after the LAST
            # marker — so any not-yet-handled records of this batch would be
            # invisible to recovery. Re-log the remainder after the marker:
            # replay skips the pre-marker copies and sees exactly the record
            # sequence per-record sync would have produced. (Own messages
            # for the new height cannot be in the remainder — the state
            # machine only enqueues them after the commit ran, i.e. into a
            # later batch — so the re-log needs no fsync of its own.)
            for i, it in enumerate(loggable):
                committed_h = self.state.last_block_height
                try:
                    if isinstance(it, TimeoutInfo):
                        self._handle_timeout(it)
                    elif isinstance(it, _MsgInfo):
                        self._handle_msg(it)
                    elif it == "txs_available":
                        self._handle_txs_available()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("error in consensus receive routine "
                                     "(height=%d round=%d step=%s)",
                                     self.rs.height, self.rs.round, self.rs.step)
                rest = loggable[i + 1:]
                if self.state.last_block_height == committed_h or not rest:
                    continue
                try:
                    with self.wal.group():
                        for rem in rest:
                            if isinstance(rem, TimeoutInfo):
                                self.wal.write_timeout(rem, now_ns())
                            elif isinstance(rem, _MsgInfo):
                                self.wal.write_msg_info(
                                    rem.msg, rem.peer_id, now_ns(),
                                    internal=rem.peer_id == "")
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # the pre-marker copies are still on disk (just not
                    # replayed after a crash) and any own record was already
                    # fsynced in phase 1 — keep handling
                    logger.exception(
                        "error re-logging batch remainder after commit "
                        "(height=%d)", self.state.last_block_height)

    def _handle_msg(self, mi: _MsgInfo) -> None:
        """(state.go:799 handleMsg)"""
        msg, peer_id = mi.msg, mi.peer_id
        if isinstance(msg, ProposalMessage):
            self._set_proposal(msg.proposal)
        elif isinstance(msg, BlockPartMessage):
            added = self._add_proposal_block_part(msg, peer_id)
            if added and self.rs.proposal_block_parts.is_complete():
                self._handle_complete_proposal(msg.height)
        elif isinstance(msg, VoteMessage):
            self._try_add_vote(msg.vote, peer_id)
        else:
            logger.error("unknown msg type %s", type(msg))

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """(state.go:890 handleTimeout)"""
        rs = self.rs
        if (ti.height != rs.height or ti.round < rs.round
                or (ti.round == rs.round and ti.step < int(rs.step))):
            return
        step = RoundStep(ti.step)
        if step == RoundStep.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif step == RoundStep.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif step == RoundStep.PROPOSE:
            if self.event_bus:
                self.event_bus.publish_event_timeout_propose(self._round_state_event())
            self._note_round_advance("timeout_propose")
            self._enter_prevote(ti.height, ti.round)
        elif step == RoundStep.PREVOTE_WAIT:
            if self.event_bus:
                self.event_bus.publish_event_timeout_wait(self._round_state_event())
            self._note_round_advance("timeout_prevote")
            self._enter_precommit(ti.height, ti.round)
        elif step == RoundStep.PRECOMMIT_WAIT:
            if self.event_bus:
                self.event_bus.publish_event_timeout_wait(self._round_state_event())
            self._note_round_advance("timeout_precommit")
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise ValueError(f"invalid timeout step: {step}")

    def _handle_txs_available(self) -> None:
        """(state.go:939 handleTxsAvailable)"""
        if self.rs.round != 0:
            return
        if self.rs.step == RoundStep.NEW_HEIGHT:
            if self._need_proof_block(self.rs.height):
                return
            timeout_commit = (self.rs.start_time_ns - now_ns()) / 1e9 + 0.001
            self._schedule_timeout(max(timeout_commit, 0.001), self.rs.height, 0,
                                   RoundStep.NEW_ROUND)
        elif self.rs.step == RoundStep.NEW_ROUND:
            self._enter_propose(self.rs.height, 0)

    def notify_txs_available(self) -> None:
        self._queue.put_nowait("txs_available")

    # -- state update ------------------------------------------------------

    def update_to_state(self, state: State) -> None:
        """(state.go:574 updateToState)"""
        from ..crypto import schemes

        # idempotent: keeps the scheme registry current with the chain's
        # consensus params (they can change via EndBlock updates)
        schemes.register_chain(state.chain_id,
                               state.consensus_params.signature)
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState() expected state height of {rs.height} but found "
                f"{state.last_block_height}")
        if not self.state.is_empty():
            if (self.state.last_block_height > 0
                    and self.state.last_block_height + 1 != rs.height):
                raise RuntimeError(
                    f"inconsistent cs.state.LastBlockHeight+1 "
                    f"{self.state.last_block_height + 1} vs cs.Height {rs.height}")
            if state.last_block_height <= self.state.last_block_height:
                self._new_step()
                return

        if state.last_block_height == 0:
            rs.last_commit = None
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if not precommits.has_two_thirds_majority():
                raise RuntimeError(
                    f"wanted to form a commit, but precommits (H/R: "
                    f"{state.last_block_height}/{rs.commit_round}) didn't have 2/3+")
            rs.last_commit = precommits
        elif rs.last_commit is None:
            raise RuntimeError(
                f"last commit cannot be empty after initial block "
                f"(H:{state.last_block_height + 1})")

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = RoundStep.NEW_HEIGHT
        if rs.commit_time_ns == 0:
            rs.start_time_ns = self.config.commit_time_ns(now_ns())
        else:
            rs.start_time_ns = self.config.commit_time_ns(rs.commit_time_ns)
        rs.validators = state.validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, state.validators,
                                 verifier=self.vote_verifier)
        rs.commit_round = -1
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self.timeline.begin_height(height)
        self._new_step()

    def reconstruct_last_commit(self, state: State) -> None:
        """(state.go:550 reconstructLastCommit)"""
        if state.last_block_height == 0:
            return
        seen_commit = self.block_store.load_seen_commit(state.last_block_height)
        if seen_commit is None:
            raise RuntimeError(
                f"failed to reconstruct last commit; seen commit for height "
                f"{state.last_block_height} not found")
        last_precommits = commit_to_vote_set(state.chain_id, seen_commit,
                                             state.last_validators)
        if not last_precommits.has_two_thirds_majority():
            raise RuntimeError("failed to reconstruct last commit; does not have +2/3 maj")
        self.rs.last_commit = last_precommits

    def _new_step(self) -> None:
        rs_event = self._round_state_event()
        self.wal.write_round_step(self.rs.height, self.rs.round, int(self.rs.step),
                                  now_ns())
        self.n_steps += 1
        if self.event_bus is not None:
            self.event_bus.publish_event_new_round_step(rs_event)
        for listener in self.new_round_step_listeners:
            listener(self.rs)

    def _round_state_event(self) -> EventDataRoundState:
        return EventDataRoundState(self.rs.height, self.rs.round,
                                   self.rs.step.short_name())

    # -- step transitions --------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """(state.go:976)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT)):
            return
        logger.debug("entering new round %d/%d", height, round_)

        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)

        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round for skipping
        rs.triggered_timeout_precommit = False

        if self.event_bus:
            proposer = validators.get_proposer()
            idx, _ = validators.get_by_address(proposer.address)
            self.event_bus.publish_event_new_round(EventDataNewRound(
                height, round_, rs.step.short_name(), proposer.address, idx))

        wait_for_txs = (self.config.wait_for_txs() and round_ == 0
                        and not self._need_proof_block(height))
        if wait_for_txs:
            if self.config.create_empty_blocks_interval > 0:
                self._schedule_timeout(self.config.create_empty_blocks_interval,
                                       height, round_, RoundStep.NEW_ROUND)
            # else wait for notify_txs_available
        else:
            self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        if height == self.state.initial_height:
            return True
        last_meta = self.block_store.load_block_meta(height - 1)
        if last_meta is None:
            raise RuntimeError(f"needProofBlock: last block meta for height {height - 1} not found")
        return self.state.app_hash != last_meta.header.app_hash

    def _enter_propose(self, height: int, round_: int) -> None:
        """(state.go:1060)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.step >= RoundStep.PROPOSE)):
            return
        logger.debug("entering propose %d/%d", height, round_)
        try:
            self._schedule_timeout(self._round_timeout_s("propose", round_),
                                   height, round_, RoundStep.PROPOSE)
            if self.priv_validator is None or self.priv_validator_pub_key is None:
                return
            address = self.priv_validator_pub_key.address()
            if not rs.validators.has_address(address):
                return
            if rs.validators.get_proposer().address == address:
                self._decide_proposal(height, round_)
        finally:
            rs.round = round_
            rs.step = RoundStep.PROPOSE
            self._new_step()
            if self._is_proposal_complete():
                self._enter_prevote(height, rs.round)

    def _decide_proposal(self, height: int, round_: int) -> None:
        """(state.go:1124 defaultDecideProposal)"""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            commit: Optional[Commit]
            if height == self.state.initial_height:
                commit = Commit(0, 0, BlockID(), [])
            elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
                commit = rs.last_commit.make_commit()
            else:
                logger.error("propose step; cannot propose anything without commit for the previous block")
                return
            proposer_addr = self.priv_validator_pub_key.address()
            block, block_parts = self.block_exec.create_proposal_block(
                height, self.state, commit, proposer_addr)

        block_id = BlockID(block.hash(), block_parts.header())
        proposal = Proposal(height, round_, rs.valid_round, block_id,
                            self._now_ns())
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            if not self._replay_mode:
                logger.error("propose step; failed signing proposal: %s", e)
            return
        self.send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total):
            self.send_internal(BlockPartMessage(rs.height, round_, block_parts.get_part(i)))
        # ingestion-plane lifecycle: the proposer stamps proposal_included
        # at creation (followers stamp at complete-proposal decode)
        tl = self._txlife()
        if tl is not None and tl.tracking():
            for tx in block.data.txs:
                tl.mark_tx(tx, "proposal_included", height=height)
        logger.info("signed proposal %d/%d", height, round_)

    def _txlife(self):
        """The per-node tx lifecycle tracker (libs/txlife.py), reached
        through the mempool it is wired onto (NoOpMempool and bare test
        mempools simply have none)."""
        return getattr(getattr(self.block_exec, "mempool", None),
                       "txlife", None)

    def _is_proposal_complete(self) -> bool:
        """(state.go isProposalComplete)"""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """(state.go:1226)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.step >= RoundStep.PREVOTE)):
            return
        logger.debug("entering prevote %d/%d", height, round_)
        self._do_prevote(height, round_)
        rs.round = round_
        rs.step = RoundStep.PREVOTE
        self._new_step()

    def _do_prevote(self, height: int, round_: int) -> None:
        """(state.go:1252 defaultDoPrevote; maverick hook at the top —
        misbehavior.go PrevoteForBlockAndNil)"""
        rs = self.rs
        if self.misbehaviors.get(height) == "double-prevote" \
                and rs.proposal_block is not None \
                and self.priv_validator is not None:
            logger.warning("MISBEHAVIOR double-prevote at height %d", height)
            self._sign_add_vote(SignedMsgType.PREVOTE, rs.proposal_block.hash(),
                                rs.proposal_block_parts.header())
            try:
                # equivocate: a second, conflicting nil prevote straight to
                # the reactors (our own VoteSet would reject it; peers must
                # see it). A refusing signer (FilePV) must not abort the
                # step transition — misbehaving is best-effort.
                nil_vote = self._sign_vote(SignedMsgType.PREVOTE, b"",
                                           PartSetHeader())
                for listener in self.equivocation_listeners:
                    listener(nil_vote)
            except Exception as e:
                logger.error("double-prevote equivocation refused: %s", e)
            return
        if rs.locked_block is not None:
            self._sign_add_vote(SignedMsgType.PREVOTE, rs.locked_block.hash(),
                                rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self._sign_add_vote(SignedMsgType.PREVOTE, b"", PartSetHeader())
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
            self._check_aggregated_commit_time(rs.proposal_block)
        except Exception as e:
            logger.error("prevote step: ProposalBlock is invalid: %s", e)
            self._sign_add_vote(SignedMsgType.PREVOTE, b"", PartSetHeader())
            return
        self._sign_add_vote(SignedMsgType.PREVOTE, rs.proposal_block.hash(),
                            rs.proposal_block_parts.header())

    def _check_aggregated_commit_time(self, block) -> None:
        """Subjective BFT-time guard for aggregated commits, prevote-only.

        Aggregated precommits sign zero-timestamp bytes (schemes
        AGG_ZERO_TS_NS), so AggregatedCommit.timestamp_ns — and with it
        header.time_ns, which validate_block pins to it — is
        proposer-assembled and covered by NO signature.  Deterministic
        validation can only enforce monotonicity; the rest of BFT time is
        recovered here, subjectively, before prevoting: the proposed
        last-commit timestamp must sit within agg_commit_time_drift_s of
        the precommit timestamps this node itself recorded for the previous
        height (when it tracked them) and never run ahead of the local
        clock by more than the drift.  A proposer-invented future time then
        draws nil prevotes from every honest validator and cannot reach a
        quorum.  Plain CommitSig commits carry signed per-vote timestamps
        and need none of this."""
        commit = block.last_commit
        if commit is None or not hasattr(commit, "agg_sig"):
            return
        drift_s = self.config.agg_commit_time_drift_s
        if drift_s <= 0:
            return
        seen_ts = []
        if self.rs.last_commit is not None:
            seen_ts = [v.timestamp_ns for v in self.rs.last_commit.list_votes()
                       if v.block_id == commit.block_id
                       and commit.signers.get_index(v.validator_index)]
        check_aggregated_commit_time(commit, seen_ts, self._now_ns(),
                                     int(drift_s * 1e9))

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """(state.go:1286)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT)):
            return
        if not rs.votes.prevotes(round_).has_two_thirds_any():
            raise RuntimeError(
                f"entering prevote wait step ({height}/{round_}), but prevotes "
                f"does not have any +2/3 votes")
        logger.debug("entering prevote wait %d/%d", height, round_)
        rs.round = round_
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(self._round_timeout_s("prevote", round_),
                               height, round_, RoundStep.PREVOTE_WAIT)

    def _enter_precommit(self, height: int, round_: int) -> None:
        """(state.go:1322)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.step >= RoundStep.PRECOMMIT)):
            return
        logger.debug("entering precommit %d/%d", height, round_)

        def done():
            rs.round = round_
            rs.step = RoundStep.PRECOMMIT
            self._new_step()

        block_id, ok = rs.votes.prevotes(round_).two_thirds_majority()

        if not ok:
            self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
            done()
            return

        if self.event_bus:
            self.event_bus.publish_event_polka(self._round_state_event())

        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise RuntimeError(f"this POLRound should be {round_} but got {pol_round}")

        # +2/3 prevoted nil: unlock and precommit nil
        if len(block_id.hash) == 0:
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self.event_bus:
                    self.event_bus.publish_event_unlock(self._round_state_event()) \
                        if hasattr(self.event_bus, "publish_event_unlock") else None
            self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
            done()
            return

        # already locked on this block: relock
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.locked_round = round_
            if self.event_bus:
                self.event_bus.publish_event_relock(self._round_state_event())
            self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash,
                                block_id.part_set_header)
            done()
            return

        # +2/3 prevoted our proposal block: lock it
        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            self.block_exec.validate_block(self.state, rs.proposal_block)  # panics on bad
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            if self.event_bus:
                self.event_bus.publish_event_lock(self._round_state_event())
            self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash,
                                block_id.part_set_header)
            done()
            return

        # polka for a block we don't have: unlock, fetch, precommit nil
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.part_set_header):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
        self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
        done()

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """(state.go:1439)"""
        rs = self.rs
        if (rs.height != height or round_ < rs.round
                or (rs.round == round_ and rs.triggered_timeout_precommit)):
            return
        if not rs.votes.precommits(round_).has_two_thirds_any():
            raise RuntimeError(
                f"entering precommit wait step ({height}/{round_}), but precommits "
                f"does not have any +2/3 votes")
        logger.debug("entering precommit wait %d/%d", height, round_)
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(self._round_timeout_s("precommit", round_),
                               height, round_, RoundStep.PRECOMMIT_WAIT)

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """(state.go:1476)"""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        logger.debug("entering commit %d/%d", height, commit_round)

        try:
            block_id, ok = rs.votes.precommits(commit_round).two_thirds_majority()
            if not ok:
                raise RuntimeError("enterCommit expects +2/3 precommits")

            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.proposal_block = rs.locked_block
                rs.proposal_block_parts = rs.locked_block_parts

            if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
                if (rs.proposal_block_parts is None
                        or not rs.proposal_block_parts.has_header(block_id.part_set_header)):
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
                    if self.event_bus:
                        self.event_bus.publish_event_valid_block(self._round_state_event())
                    for listener in self.valid_block_listeners:
                        listener(rs)
        finally:
            # keep rs.round; commit_round points at the right precommit set
            rs.step = RoundStep.COMMIT
            rs.commit_round = commit_round
            rs.commit_time_ns = self._now_ns()
            self._new_step()
            self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        """(state.go:1539)"""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError(f"tryFinalizeCommit() cs.Height: {rs.height} vs {height}")
        block_id, ok = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        if not ok or len(block_id.hash) == 0:
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """(state.go:1567)"""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        block_id, ok = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok:
            raise RuntimeError("cannot finalize commit; commit does not have 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("expected ProposalBlockParts header to be commit header")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize commit; proposal block does not hash to commit hash")
        self.block_exec.validate_block(self.state, block)

        logger.info("finalizing commit of block height=%d hash=%s txs=%d",
                    height, block.hash().hex()[:12], len(block.data.txs))

        # seals the height's stage timeline: observes stage_seconds and
        # emits the per-stage trace spans (consensus/timeline.py)
        self.timeline.mark(height, rs.commit_round, "commit_finalized")

        # seal sampled tx lifecycles at the consensus commit point; the
        # mempool.update() mark inside apply_block is the fallback for
        # blocks applied off the consensus path (fast sync)
        tl = self._txlife()
        if tl is not None and tl.tracking():
            for tx in block.data.txs:
                tl.mark_tx(tx, "committed", height=height)

        if self.metrics is not None:
            self._record_commit_metrics(block)
            self.metrics.rounds_per_height.observe(rs.commit_round + 1)

        if self.block_store.height() < block.header.height:
            seen_commit = rs.votes.precommits(rs.commit_round).make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)

        from ..libs.fail import fail_point

        fail_point("consensus.commit.before_end_height")  # (consensus/state.go:776 fail.Fail precommit->commit)
        # EndHeight implies blockstore has the block (crash recovery pivot).
        self.wal.write_end_height(height, now_ns())

        state_copy = self.state.copy()
        state_copy, retain_height = self.block_exec.apply_block(
            state_copy, BlockID(block.hash(), block_parts.header()), block)

        if retain_height > 0:
            try:
                pruned = self.block_store.prune_blocks(retain_height)
                self.block_exec.state_store.prune_states(retain_height)
                logger.debug("pruned %d blocks to retain height %d", pruned, retain_height)
            except Exception as e:
                logger.error("failed to prune blocks: %s", e)

        self.update_to_state(state_copy)
        if self.priv_validator is not None:
            self.priv_validator_pub_key = self.priv_validator.get_pub_key()
        self._schedule_round0()

    # -- proposals ---------------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """(state.go:1808 defaultSetProposal)"""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (0 <= proposal.pol_round >= proposal.round):
            raise ValueError("error invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
                proposal.sign_bytes(self.state.chain_id), proposal.signature):
            raise ValueError("error invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(proposal.block_id.part_set_header)
        self.timeline.mark(proposal.height, proposal.round,
                           "proposal_received")
        logger.info("received proposal %d/%d", proposal.height, proposal.round)
        for listener in self.proposal_data_listeners:
            listener()

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> bool:
        """(state.go:1850)"""
        rs = self.rs
        if rs.height != msg.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if added:
            for listener in self.proposal_data_listeners:
                listener()
        if rs.proposal_block_parts.byte_size > self.state.consensus_params.block.max_bytes:
            raise ValueError(
                f"total size of proposal block parts exceeds maximum block bytes "
                f"({rs.proposal_block_parts.byte_size} > "
                f"{self.state.consensus_params.block.max_bytes})")
        if added and rs.proposal_block_parts.is_complete():
            rs.proposal_block = Block.decode(rs.proposal_block_parts.get_reader())
            logger.info("received complete proposal block height=%d hash=%s",
                        rs.proposal_block.header.height,
                        (rs.proposal_block.hash() or b"").hex()[:12])
            # followers stamp proposal_included when the block decodes —
            # the earliest point this node can attribute txs to a height
            tl = self._txlife()
            if tl is not None and tl.tracking():
                for tx in rs.proposal_block.data.txs:
                    tl.mark_tx(tx, "proposal_included",
                               height=rs.proposal_block.header.height)
            if self.event_bus:
                self.event_bus.publish_event_complete_proposal(
                    EventDataCompleteProposal(
                        rs.height, rs.round, rs.step.short_name(),
                        BlockID(rs.proposal_block.hash(),
                                rs.proposal_block_parts.header())))
        return added

    def _handle_complete_proposal(self, block_height: int) -> None:
        """(state.go:1911)"""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round)
        block_id, has_two_thirds = (prevotes.two_thirds_majority()
                                    if prevotes else (BlockID(), False))
        if (has_two_thirds and not block_id.is_zero() and rs.valid_round < rs.round
                and rs.proposal_block.hash() == block_id.hash):
            rs.valid_round = rs.round
            rs.valid_block = rs.proposal_block
            rs.valid_block_parts = rs.proposal_block_parts
        if rs.step <= RoundStep.PROPOSE and self._is_proposal_complete():
            self._enter_prevote(block_height, rs.round)
            if has_two_thirds:
                self._enter_precommit(block_height, rs.round)
        elif rs.step == RoundStep.COMMIT:
            self._try_finalize_commit(block_height)

    # -- votes -------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """(state.go:1947)"""
        try:
            return self._add_vote(vote, peer_id)
        except ErrVoteConflictingVotes as e:
            if self.priv_validator_pub_key is not None and \
                    vote.validator_address == self.priv_validator_pub_key.address():
                logger.error(
                    "found conflicting vote from ourselves; did you unsafe_reset a validator? "
                    "height=%d round=%d", vote.height, vote.round)
                return False
            if self.evpool is not None:
                self.evpool.report_conflicting_votes(e.vote_a, e.vote_b)
            logger.debug("found and sent conflicting votes to the evidence pool")
            return False
        except VoteSetError as e:
            logger.info("failed attempting to add vote: %s", e)
            return False
        except Exception as e:
            logger.info("failed attempting to add vote: %s", e)
            return False

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """(state.go:1995)"""
        rs = self.rs

        # A precommit for the previous height (during timeoutCommit wait)
        if vote.height + 1 == rs.height and vote.type == SignedMsgType.PRECOMMIT:
            if rs.step != RoundStep.NEW_HEIGHT:
                return False
            added = rs.last_commit.add_vote(vote)
            if not added:
                return False
            if self.event_bus:
                from ..types.event_bus import EventDataVote

                self.event_bus.publish_event_vote(vote)
            for listener in self.vote_listeners:
                listener(vote)
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)
            return True

        if vote.height != rs.height:
            return False

        height = rs.height
        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        if self.event_bus:
            self.event_bus.publish_event_vote(vote)
        for listener in self.vote_listeners:
            listener(vote)

        if vote.type == SignedMsgType.PREVOTE:
            prevotes = rs.votes.prevotes(vote.round)
            if (not self.timeline.marked(height, "prevote_quorum")
                    and prevotes.has_two_thirds_any()):
                self.timeline.mark(height, vote.round, "prevote_quorum")
            block_id, ok = prevotes.two_thirds_majority()
            if ok:
                # unlock on newer POL for a different block
                if (rs.locked_block is not None and rs.locked_round < vote.round
                        and vote.round <= rs.round
                        and rs.locked_block.hash() != block_id.hash):
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                # update Valid*
                if (len(block_id.hash) != 0 and rs.valid_round < vote.round
                        and vote.round == rs.round):
                    if (rs.proposal_block is not None
                            and rs.proposal_block.hash() == block_id.hash):
                        rs.valid_round = vote.round
                        rs.valid_block = rs.proposal_block
                        rs.valid_block_parts = rs.proposal_block_parts
                    else:
                        rs.proposal_block = None
                    if (rs.proposal_block_parts is None
                            or not rs.proposal_block_parts.has_header(
                                block_id.part_set_header)):
                        rs.proposal_block_parts = PartSet.from_header(
                            block_id.part_set_header)
                    for listener in self.valid_block_listeners:
                        listener(rs)
                    if self.event_bus:
                        self.event_bus.publish_event_valid_block(self._round_state_event())

            if rs.round < vote.round and prevotes.has_two_thirds_any():
                self._note_round_advance("polka_skip")
                self._enter_new_round(height, vote.round)
            elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
                block_id, ok = prevotes.two_thirds_majority()
                if ok and (self._is_proposal_complete() or len(block_id.hash) == 0):
                    self._enter_precommit(height, vote.round)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(height, vote.round)
            elif (rs.proposal is not None and 0 <= rs.proposal.pol_round
                  and rs.proposal.pol_round == vote.round):
                if self._is_proposal_complete():
                    self._enter_prevote(height, rs.round)

        elif vote.type == SignedMsgType.PRECOMMIT:
            precommits = rs.votes.precommits(vote.round)
            if (not self.timeline.marked(height, "precommit_quorum")
                    and precommits.has_two_thirds_any()):
                self.timeline.mark(height, vote.round, "precommit_quorum")
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                self._enter_new_round(height, vote.round)
                self._enter_precommit(height, vote.round)
                if len(block_id.hash) != 0:
                    self._enter_commit(height, vote.round)
                    if self.config.skip_timeout_commit and precommits.has_all():
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(height, vote.round)
            elif rs.round <= vote.round and precommits.has_two_thirds_any():
                if rs.round < vote.round:
                    self._note_round_advance("polka_skip")
                self._enter_new_round(height, vote.round)
                self._enter_precommit_wait(height, vote.round)
        else:
            raise ValueError(f"unexpected vote type {vote.type}")
        return True

    # -- signing -----------------------------------------------------------

    def _vote_time_ns(self) -> int:
        """(state.go:2204 voteTime) — BFT time monotonicity. Reads the
        skewed clock (_now_ns): a node with a fast/slow wall clock stamps
        its votes accordingly, and the max() against the locked/proposal
        block time keeps BFT-time monotone regardless of the skew sign."""
        now = self._now_ns()
        min_vote_time = now
        time_iota_ns = self.state.consensus_params.block.time_iota_ms * 1_000_000
        if self.rs.locked_block is not None:
            min_vote_time = self.rs.locked_block.header.time_ns + time_iota_ns
        elif self.rs.proposal_block is not None:
            min_vote_time = self.rs.proposal_block.header.time_ns + time_iota_ns
        return now if now > min_vote_time else min_vote_time

    def _sign_vote(self, msg_type: SignedMsgType, hash_: bytes,
                   header: PartSetHeader) -> Vote:
        """(state.go:2172 signVote)"""
        self.wal.flush_and_sync()
        addr = self.priv_validator_pub_key.address()
        val_idx, _ = self.rs.validators.get_by_address(addr)
        vote = Vote(
            type=msg_type,
            height=self.rs.height,
            round=self.rs.round,
            block_id=BlockID(hash_, header),
            timestamp_ns=self._vote_time_ns(),
            validator_address=addr,
            validator_index=val_idx,
        )
        self.priv_validator.sign_vote(self.state.chain_id, vote)
        return vote

    def _sign_add_vote(self, msg_type: SignedMsgType, hash_: bytes,
                       header: PartSetHeader) -> Optional[Vote]:
        """(state.go:2227 signAddVote)"""
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        if not self.rs.validators.has_address(self.priv_validator_pub_key.address()):
            return None
        try:
            vote = self._sign_vote(msg_type, hash_, header)
        except Exception as e:
            if not self._replay_mode:
                logger.error("failed signing vote height=%d round=%d: %s",
                             self.rs.height, self.rs.round, e)
            return None
        self.send_internal(VoteMessage(vote))
        self.timeline.mark(self.rs.height, self.rs.round,
                           "prevote_sent" if msg_type == SignedMsgType.PREVOTE
                           else "precommit_sent")
        return vote
