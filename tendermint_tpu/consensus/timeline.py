"""Per-height consensus stage timeline (cluster observability plane).

The reference explains a slow height by reading four nodes' logs; the papers
this build tracks (arXiv 2302.00418, 2410.03347) attribute every win via a
per-phase latency decomposition of the consensus round. This module records
that decomposition live, per height: wall-clock marks at

    proposal_received   the proposal message was accepted by the state machine
    prevote_sent        our own prevote was signed and enqueued
    prevote_quorum      2/3+ prevotes seen for the round
    precommit_sent      our own precommit was signed and enqueued
    precommit_quorum    2/3+ precommits seen for the round
    commit_finalized    the block passed final validation and is committing

plus an auxiliary ``proposal_wire`` mark stamped by the reactor at wire
receipt (the gap to ``proposal_received`` is the state-machine queue delay).

When a height seals at ``commit_finalized`` the timeline:

* observes the interval between consecutive marks into
  ``ConsensusMetrics.stage_seconds`` (series
  ``tendermint_consensus_stage_seconds{stage=...}``),
* emits one height-tagged complete span per stage interval
  (``stage_<name>``) into the process tracer, so bench per-height
  breakdowns and the cross-node merged timeline (tools/trace_merge.py)
  show WHERE each height's wall-clock went,
* appends a JSON-safe record to a bounded ring queryable over RPC
  (``/consensus_stage_timeline``) and included in debugdump bundles.

All marks happen inside the single-writer consensus loop, so recording is
lock-free; readers (RPC handlers on the same loop, the debugdump signal
handler, the watchdog thread) only ever see fully-built records because a
record is appended to the ring in one bytecode after construction.

Marks store BOTH clocks: ``time.time()`` for cross-node skew (nodes on one
box share a wall clock; across boxes NTP bounds it) and
``time.perf_counter()`` for durations (wall clock can step backwards).
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional

from ..libs.trace import tracer

#: canonical stage order within a height — durations are deltas between
#: consecutive PRESENT stages in this order (a non-validator never marks
#: the *_sent stages; its deltas bridge straight across)
STAGES = ("proposal_received", "prevote_sent", "prevote_quorum",
          "precommit_sent", "precommit_quorum", "commit_finalized")

DEFAULT_CAPACITY = 256


class StageTimeline:
    """Bounded per-height stage-mark recorder for one ConsensusState."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._ring: "collections.deque" = collections.deque(maxlen=capacity)
        self.metrics = None  # ConsensusMetrics, wired by the node
        #: seal observer: called with the sealed height's durations dict
        #: from inside the single-writer loop — the adaptive-timeout
        #: controller's observation stream (consensus/config.py)
        self.on_seal = None
        self._cur: Optional[dict] = None
        self.heights_sealed = 0
        #: replay guard: WAL catchup re-feeds old messages through the
        #: state machine microseconds apart — those marks are replay time,
        #: not consensus time, and would seal one garbage record (and
        #: garbage stage_seconds samples) per restart.
        #: consensus/replay.py disables recording around catchup; the
        #: first live height then opens a fresh record at its first mark.
        self.enabled = True

    # -- recording (single-writer consensus loop) -------------------------

    def begin_height(self, height: int) -> None:
        """Open a record for ``height``; called from update_to_state. An
        unsealed predecessor (height overtaken by fast sync, or abandoned
        mid-round at a restart) is pushed as-is so the ring shows the gap."""
        if not self.enabled:
            return
        cur = self._cur
        if cur is not None and cur["height"] == height:
            return
        if cur is not None:
            self._ring.append(self._view(cur))
        self._cur = {
            "height": height,
            "round": 0,
            "t0_wall": time.time(),
            "t0_perf": time.perf_counter(),
            "marks": [],           # (stage, round, t_wall, t_perf) in order
            "_by_stage": {},       # stage -> (round, t_wall, t_perf), last wins
            "sealed": False,
        }

    def mark(self, height: int, round_: int, stage: str) -> None:
        if not self.enabled:
            return
        cur = self._cur
        if cur is None or height > cur["height"]:
            # marks can precede update_to_state only at process start
            self.begin_height(height)
            cur = self._cur
        elif height < cur["height"]:
            return  # stale (e.g. a WAL-replayed message for an old height)
        t_wall, t_perf = time.time(), time.perf_counter()
        if round_ > cur["round"]:
            cur["round"] = round_
        cur["marks"].append((stage, round_, t_wall, t_perf))
        cur["_by_stage"][stage] = (round_, t_wall, t_perf)
        if stage == "commit_finalized":
            self._seal(cur)

    def marked(self, height: int, stage: str) -> bool:
        """Cheap dedup guard for per-vote quorum checks."""
        cur = self._cur
        return (cur is not None and cur["height"] == height
                and stage in cur["_by_stage"])

    def note_wire_proposal(self, height: int) -> None:
        """Reactor hook: earliest wire receipt of this height's proposal —
        not one of the six stages (no histogram), but the record shows the
        queue delay to ``proposal_received``."""
        if not self.enabled:
            return
        cur = self._cur
        if (cur is None or cur["height"] != height
                or "proposal_wire" in cur["_by_stage"]):
            return
        t_wall, t_perf = time.time(), time.perf_counter()
        cur["marks"].append(("proposal_wire", -1, t_wall, t_perf))
        cur["_by_stage"]["proposal_wire"] = (-1, t_wall, t_perf)

    def _seal(self, cur: dict) -> None:
        by = cur["_by_stage"]
        durations: Dict[str, float] = {}
        prev = cur["t0_perf"]
        for stage in STAGES:
            got = by.get(stage)
            if got is None:
                continue
            t_perf = got[2]
            durations[stage] = max(0.0, t_perf - prev)
            prev = max(prev, t_perf)
        cur["durations"] = durations
        cur["total_s"] = max(0.0, by["commit_finalized"][2] - cur["t0_perf"])
        cur["sealed"] = True
        self.heights_sealed += 1
        m = self.metrics
        if m is not None:
            for stage, d in durations.items():
                m.stage_seconds.labels(stage).observe(d)
        cb = self.on_seal
        if cb is not None:
            cb(dict(durations))
        if tracer.enabled:
            prev = cur["t0_perf"]
            for stage in STAGES:
                got = by.get(stage)
                if got is None:
                    continue
                r, _, t_perf = got
                start = min(prev, t_perf)
                tracer.complete(f"stage_{stage}", start * 1e6,
                                max(0.0, t_perf - start) * 1e6,
                                height=cur["height"], round=r)
                prev = max(prev, t_perf)
        self._ring.append(self._view(cur))
        self._cur = None

    # -- queries (RPC / debugdump / bench) ---------------------------------

    @staticmethod
    def _view(cur: dict) -> dict:
        rec = {
            "height": cur["height"],
            "round": cur["round"],
            "t0_wall": cur["t0_wall"],
            "sealed": cur["sealed"],
            "marks": [[stage, r, t_wall]
                      for stage, r, t_wall, _ in cur["marks"]],
        }
        if cur["sealed"]:
            rec["durations"] = {s: round(d, 6)
                                for s, d in cur["durations"].items()}
            rec["total_s"] = round(cur["total_s"], 6)
        return rec

    def tail(self, n: int) -> List[dict]:
        records = list(self._ring)
        return records[-n:] if n < len(records) else records

    def snapshot(self, limit: int = 20) -> dict:
        cur = self._cur
        return {
            "capacity": self.capacity,
            "heights_sealed": self.heights_sealed,
            "current": self._view(cur) if cur is not None else None,
            "heights": self.tail(max(1, int(limit))),
        }
