"""Columnar sign-bytes: the zero-copy vote-pack fast path.

One commit's canonical sign-bytes share every byte except a handful of
timestamp positions (types/canonical.py vote_sign_bytes_batch builds them
from cached shared pieces for exactly that reason). The batched device
verifier then re-DISCOVERS that structure per segment: it joins all rows
into one (n, mlen) matrix and diff-scans it against per-chunk templates
(ed25519_jax/verify.prepare_sparse_stream) — O(n*mlen) of memcpy + compare
per dispatch, a measurable slice of the pack share the bench gates.

:class:`SignColumns` carries the structure the encoder already knows:

* ``template`` — one full row's bytes (every row is identical outside
  ``cols``);
* ``cols``     — the int32 byte positions that vary row to row;
* ``vals``     — an (n, C) uint8 matrix of each row's bytes at ``cols``.

``types/canonical.vote_sign_bytes_columns_batch`` builds one straight from
the encoder's cached fragments (no per-row materialization, no diff scan),
``Commit.vote_sign_bytes_columns`` memoizes it per chain_id, and the
VerifyCommit* callers hand it to BatchVerifier, which threads it down to
``prepare_sparse_stream`` — the sparse wire format is assembled by slicing
these arrays instead of re-deriving them. Row reconstruction is
byte-identical to ``vote_sign_bytes_all`` (differentially tested), so
accept/reject verdicts cannot change.

numpy-only and jax-free: types/ code builds these without dragging the
device stack into encode paths.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class SignColumns:
    """A batch of equal-length messages as template + varying columns.

    Behaves as a read-only sequence of ``bytes`` rows (len / indexing /
    iteration) so host fallback paths can consume it like a message list,
    while the device pack path reads the arrays directly.
    """

    __slots__ = ("template", "cols", "vals")

    def __init__(self, template: np.ndarray, cols: np.ndarray,
                 vals: np.ndarray):
        self.template = np.ascontiguousarray(template, dtype=np.uint8)
        self.cols = np.ascontiguousarray(cols, dtype=np.int32)
        self.vals = np.asarray(vals, dtype=np.uint8)
        if self.vals.ndim != 2 or self.vals.shape[1] != self.cols.shape[0]:
            raise ValueError(
                f"vals shape {self.vals.shape} does not match "
                f"{self.cols.shape[0]} columns")

    # -- sequence protocol (host fallback / prepare_batch compatibility) ----

    def __len__(self) -> int:
        return self.vals.shape[0]

    @property
    def mlen(self) -> int:
        return self.template.shape[0]

    def __getitem__(self, i) -> bytes:
        if isinstance(i, slice):
            raise TypeError("use .slice(a, b) for row ranges")
        row = self.template.copy()
        row[self.cols] = self.vals[i]
        return row.tobytes()

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- batch views ---------------------------------------------------------

    def slice(self, a: int, b: int) -> "SignColumns":
        """Rows [a, b) — a zero-copy view (template/cols shared, vals
        sliced) for per-segment sharding."""
        return SignColumns(self.template, self.cols, self.vals[a:b])

    def subset(self, idxs: Sequence[int]) -> "SignColumns":
        """Rows at ``idxs`` in order (fancy index copies only the (k, C)
        vals block — the commit-idx candidate selection VerifyCommit*
        performs)."""
        return SignColumns(self.template, self.cols,
                           self.vals[np.asarray(idxs, dtype=np.intp)])

    def rows(self) -> list:
        """Materialized bytes rows (host fallback; O(n*mlen))."""
        n = len(self)
        arr = np.broadcast_to(self.template, (n, self.mlen)).copy()
        arr[:, self.cols] = self.vals
        return [r.tobytes() for r in arr]


def sign_columns_from_rows(rows: Sequence[bytes]) -> "Optional[SignColumns]":
    """Tx-side SignColumns analogue (mempool/ingest.py micro-batches).

    Votes get their columns from the encoder's cached fragments
    (``vote_sign_bytes_columns_batch``); tx sign-bytes have no encoder
    cache, but a micro-batch of same-shape envelopes still shares most
    bytes (magic, fee/nonce prefixes, payload padding). One vectorized
    diff-scan at PACK time — on the intake path, once per micro-batch —
    yields the same zero-copy structure, instead of the verifier
    re-discovering it per segment per dispatch.

    Returns None when there is no structure to exploit: fewer than 2
    rows, unequal lengths, or rows so dissimilar the columnar form would
    carry ≥ half the matrix anyway. Reconstruction is byte-identical to
    ``rows`` (differentially tested), so verdicts cannot change."""
    n = len(rows)
    if n < 2:
        return None
    mlen = len(rows[0])
    if mlen == 0 or any(len(r) != mlen for r in rows):
        return None
    arr = np.frombuffer(b"".join(rows), dtype=np.uint8).reshape(n, mlen)
    cols = np.flatnonzero((arr != arr[0]).any(axis=0)).astype(np.int32)
    if cols.shape[0] * 2 > mlen:
        return None
    return SignColumns(arr[0], cols, arr[:, cols])
