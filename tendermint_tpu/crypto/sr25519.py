"""sr25519 (schnorrkel/ristretto255) — the third consensus key type
(reference crypto/sr25519/pubkey.go:10, privkey.go via ChainSafe/go-schnorrkel).

Host-side pure Python, reusing the edwards25519 group from crypto/ed25519
and the merlin transcript from libs/merlin. Scalar verification never rides
the TPU kernel (SURVEY §2.3: "keep scalar on host").

Pieces, matching go-schnorrkel exactly:

* ristretto255 encode/decode (RFC 9496 §4.3) over edwards25519;
* mini-secret expansion ``ExpandEd25519``: SHA-512(mini), clamp, divide the
  key scalar by the cofactor (schnorrkel's ed25519-compat expansion);
* signing context: merlin ``Transcript("SigningContext")``,
  ``append("", ctx)``, ``append("sign-bytes", msg)``;
* sign/verify transcript: ``proto-name=Schnorr-sig``, ``sign:pk``,
  ``sign:R``, challenge scalar from 64 bytes of ``sign:c`` reduced mod L;
* signature wire form: 32-byte ristretto R || 32-byte scalar s with bit 7
  of byte 63 set (the schnorrkel "not ed25519" marker).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

from ..libs.merlin import Transcript
from . import PrivKey, PubKey, address_hash
from .ed25519 import D as _D, L, P, _IDENT, _pt_add, _pt_mul, B as _B

SEED_SIZE = 32
PUBKEY_SIZE = 32
SIGNATURE_SIZE = 64

_SQRT_M1 = pow(2, (P - 1) // 4, P)
_INVSQRT_A_MINUS_D = None  # computed lazily below
_SQRT_AD_MINUS_ONE = None
_ONE_MINUS_D_SQ = None
_D_MINUS_ONE_SQ = None


def _init_consts() -> None:
    global _INVSQRT_A_MINUS_D, _SQRT_AD_MINUS_ONE, _ONE_MINUS_D_SQ, _D_MINUS_ONE_SQ
    if _INVSQRT_A_MINUS_D is not None:
        return
    a = P - 1  # a = -1
    ok, inv_s = _sqrt_ratio_m1(1, (a - _D) % P)
    assert ok
    _INVSQRT_A_MINUS_D = inv_s
    ok, s = _sqrt_ratio_m1((a * _D - 1) % P, 1)
    assert ok
    _SQRT_AD_MINUS_ONE = s
    _ONE_MINUS_D_SQ = (1 - _D * _D) % P
    _D_MINUS_ONE_SQ = ((_D - 1) * (_D - 1)) % P


def _sqrt_ratio_m1(u: int, v: int) -> Tuple[bool, int]:
    """(RFC 9496 §4.2 SQRT_RATIO_M1) -> (was_square, sqrt(u/v) or related)."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = (u * v3 % P) * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct_sign = check == u % P
    flipped_sign = check == (-u) % P
    flipped_sign_i = check == ((-u) % P) * _SQRT_M1 % P
    if flipped_sign or flipped_sign_i:
        r = r * _SQRT_M1 % P
    if r % 2 == 1:  # use the non-negative (even) root
        r = P - r
    return correct_sign or flipped_sign, r


def ristretto_decode(b: bytes) -> Optional[Tuple[int, int, int, int]]:
    """(RFC 9496 §4.3.1 Decode) 32 bytes -> extended point, or None."""
    _init_consts()
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P or s % 2 == 1:  # canonical and non-negative
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(_D * u1 % P) * u1 % P - u2_sqr) % P
    ok, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    if not ok:
        return None
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = 2 * s % P * den_x % P
    if x % 2 == 1:
        x = P - x
    y = u1 * den_y % P
    t = x * y % P
    if t % 2 == 1 or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(pt: Tuple[int, int, int, int]) -> bytes:
    """(RFC 9496 §4.3.2 Encode) extended point -> 32 bytes."""
    _init_consts()
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) * (z0 - y0) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix0 = x0 * _SQRT_M1 % P
    iy0 = y0 * _SQRT_M1 % P
    enchanted_denominator = den1 * _INVSQRT_A_MINUS_D % P
    rotate = (t0 * z_inv % P) % 2 == 1
    if rotate:
        x, y = iy0, ix0
        den_inv = enchanted_denominator
    else:
        x, y = x0, y0
        den_inv = den2
    if (x * z_inv % P) % 2 == 1:
        y = P - y
    s = (z0 - y) * den_inv % P
    if s % 2 == 1:
        s = P - s
    return s.to_bytes(32, "little")


# -- scalars & transcripts ---------------------------------------------------

def _challenge_scalar(t: Transcript, label: bytes) -> int:
    return int.from_bytes(t.challenge_bytes(label, 64), "little") % L


def signing_context(ctx: bytes, msg: bytes) -> Transcript:
    t = Transcript(b"SigningContext")
    t.append_message(b"", ctx)
    t.append_message(b"sign-bytes", msg)
    return t


def expand_ed25519(mini: bytes) -> Tuple[int, bytes]:
    """(schnorrkel MiniSecretKey.ExpandEd25519) -> (key scalar, 32B nonce).

    Clamps like ed25519 then divides by the cofactor (the scalar is stored
    //8; schnorrkel multiplies by the untwisted basepoint directly)."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3
    return scalar % L, h[32:]


# -- sign / verify -----------------------------------------------------------

def pubkey_from_mini(mini: bytes) -> bytes:
    scalar, _ = expand_ed25519(mini)
    return ristretto_encode(_pt_mul(scalar, (_B[0], _B[1], 1, _B[0] * _B[1] % P)))


def sign(mini: bytes, msg: bytes, ctx: bytes = b"") -> bytes:
    scalar, nonce = expand_ed25519(mini)
    pub = ristretto_encode(_pt_mul(scalar, (_B[0], _B[1], 1, _B[0] * _B[1] % P)))
    t = signing_context(ctx, msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    # witness scalar: schnorrkel draws from a transcript-rng over the nonce;
    # ANY high-entropy r yields a valid signature — use hash(nonce, msg, rnd)
    r = int.from_bytes(
        hashlib.sha512(nonce + msg + os.urandom(32)).digest(), "little") % L
    R = ristretto_encode(_pt_mul(r, (_B[0], _B[1], 1, _B[0] * _B[1] % P)))
    t.append_message(b"sign:R", R)
    k = _challenge_scalar(t, b"sign:c")
    s = (k * scalar + r) % L
    sig = bytearray(R + s.to_bytes(32, "little"))
    sig[63] |= 128  # schnorrkel marker bit
    return bytes(sig)


def verify(pub: bytes, msg: bytes, sig: bytes, ctx: bytes = b"") -> bool:
    if len(sig) != SIGNATURE_SIZE or len(pub) != PUBKEY_SIZE:
        return False
    if not sig[63] & 128:  # marker bit required (Signature.Decode)
        return False
    R_bytes = sig[:32]
    s_arr = bytearray(sig[32:])
    s_arr[31] &= 127
    s = int.from_bytes(bytes(s_arr), "little")
    if s >= L:
        return False
    A = ristretto_decode(pub)
    R = ristretto_decode(R_bytes)
    if A is None or R is None:
        return False
    t = signing_context(ctx, msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    t.append_message(b"sign:R", R_bytes)
    k = _challenge_scalar(t, b"sign:c")
    # R' = s*B - k*A; ristretto equality = encoding equality
    base = (_B[0], _B[1], 1, _B[0] * _B[1] % P)
    sB = _pt_mul(s, base)
    negA = ((P - A[0]) % P, A[1], A[2], (P - A[3]) % P)
    Rp = _pt_add(sB, _pt_mul(k, negA))
    return ristretto_encode(Rp) == R_bytes


# -- key types (crypto.PubKey/PrivKey seam) ----------------------------------

class Sr25519PubKey(PubKey):
    TYPE = "sr25519"

    def __init__(self, raw: bytes):
        self._raw = bytes(raw)

    def bytes(self) -> bytes:
        return self._raw

    def address(self) -> bytes:
        return address_hash(self._raw)

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        try:
            return verify(self._raw, msg, sig)
        except Exception:
            return False

    def __eq__(self, other) -> bool:
        return isinstance(other, Sr25519PubKey) and other._raw == self._raw

    def __repr__(self) -> str:
        return f"PubKeySr25519{{{self._raw.hex().upper()}}}"


class Sr25519PrivKey(PrivKey):
    TYPE = "sr25519"

    def __init__(self, mini: bytes):
        if len(mini) != SEED_SIZE:
            raise ValueError("sr25519 private key must be a 32-byte mini secret")
        self._mini = bytes(mini)

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Sr25519PrivKey":
        return Sr25519PrivKey(seed if seed is not None else os.urandom(32))

    def bytes(self) -> bytes:
        return self._mini

    def sign(self, msg: bytes) -> bytes:
        return sign(self._mini, msg)

    def pub_key(self) -> Sr25519PubKey:
        return Sr25519PubKey(pubkey_from_mini(self._mini))
