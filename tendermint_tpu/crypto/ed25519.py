"""Host-side (scalar) Ed25519: the semantic reference implementation.

This is the framework's *specification* of signature acceptance: the TPU
batch verifier (tendermint_tpu.crypto.ed25519_jax) must make byte-identical
accept/reject decisions to :func:`verify` here, and differential tests
enforce that.

Semantics follow RFC 8032 strict verification as implemented by modern Go
``crypto/ed25519`` (which the reference uses via golang.org/x/crypto —
reference crypto/ed25519/ed25519.go:148-155):

* signature length must be 64, public key length 32;
* ``s`` (sig[32:]) must be canonical: ``s < L`` (and therefore the top three
  bits clear);
* the public key ``A`` must decode per RFC 8032 §5.1.3: ``y < p`` and
  ``x^2 = (y^2-1)/(d y^2+1)`` must have a root; if ``x == 0`` the sign bit
  must be 0;
* the check is *cofactorless*: ``[s]B == R + [h]A`` verified by comparing
  the 32-byte encoding of ``[s]B - [h]A`` against sig[:32] (R is never
  decompressed, exactly like Go's implementation).

Pure Python (hashlib + int arithmetic): slow (~1 ms/verify) but exact.
The fast host path used in production defaults is `cryptography` (OpenSSL);
see batch.py for the dispatch.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

# --- curve constants -------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# base point
_BY = 4 * pow(5, P - 2, P) % P
_BX = None  # filled below


def _recover_x(y: int, sign: int) -> Optional[int]:
    """RFC 8032 §5.1.3 x-recovery. Returns None on failure."""
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root of u/v: x = u v^3 (u v^7)^((p-5)/8)
    x = (u * pow(v, 3, P)) * pow(u * pow(v, 7, P), (P - 5) // 8, P) % P
    vxx = v * x * x % P
    if vxx == u:
        pass
    elif vxx == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
B = (_BX, _BY)  # base point, affine


# --- group ops (affine-free: extended homogeneous (X,Y,Z,T)) ---------------

def _pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    Bv = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dv = 2 * Z1 * Z2 % P
    E, F, G, H = Bv - A, Dv - C, Dv + C, Bv + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _pt_dbl(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    Bv = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = (A + Bv) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - Bv) % P
    F = (C + G) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


_IDENT = (0, 1, 1, 0)


def _pt_mul(s: int, p) -> Tuple[int, int, int, int]:
    q = _IDENT
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_dbl(p)
        s >>= 1
    return q


def _pt_encode(p) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x, y = X * zi % P, Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _pt_decode(s: bytes):
    """Decode 32-byte point encoding → extended coords, or None (strict)."""
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    y = val & ((1 << 255) - 1)
    sign = val >> 255
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


# --- keys & signing --------------------------------------------------------

SEED_SIZE = 32
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64  # seed || pubkey, matching the reference's 64-byte privkey
SIGNATURE_SIZE = 64


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def pubkey_from_seed(seed: bytes) -> bytes:
    if len(seed) != SEED_SIZE:
        raise ValueError(f"ed25519 seed must be {SEED_SIZE} bytes, got {len(seed)}")
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    return _pt_encode(_pt_mul(a, (B[0], B[1], 1, B[0] * B[1] % P)))


def keygen(seed: Optional[bytes] = None) -> Tuple[bytes, bytes]:
    """Returns (priv, pub); priv = seed || pub (64 bytes, like the reference)."""
    if seed is None:
        seed = os.urandom(SEED_SIZE)
    pub = pubkey_from_seed(seed)
    return seed + pub, pub


def sign(priv: bytes, msg: bytes) -> bytes:
    if len(priv) != PRIVKEY_SIZE:
        raise ValueError(f"ed25519 private key must be {PRIVKEY_SIZE} bytes, got {len(priv)}")
    seed, pub = priv[:32], priv[32:]
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = _pt_encode(_pt_mul(r, (B[0], B[1], 1, B[0] * B[1] % P)))
    k = int.from_bytes(hashlib.sha512(R + pub + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Strict cofactorless verification; the acceptance spec for the framework."""
    if len(pub) != PUBKEY_SIZE or len(sig) != SIGNATURE_SIZE:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    A = _pt_decode(pub)
    if A is None:
        return False
    h = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
    # R' = [s]B - [h]A ; accept iff encode(R') == sig[:32]
    negA = (P - A[0], A[1], A[2], P - A[3])
    sB = _pt_mul(s, (B[0], B[1], 1, B[0] * B[1] % P))
    hA = _pt_mul(h, negA)
    return _pt_encode(_pt_add(sB, hA)) == sig[:32]
