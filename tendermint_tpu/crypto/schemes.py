"""Per-chain signature-scheme registry (the scheme-agnostic crypto plane).

The consensus params of a chain (types/params.SignatureParams) say which
signature scheme its validators use and whether commits are aggregated.
Everything that builds or checks vote sign-bytes — signers, VoteSet, commit
rebuilds, evidence — asks this registry instead of assuming ed25519, keyed
by chain_id because sign-bytes only ever exist relative to a chain.

Registration happens wherever a chain's params become known:
`state_from_genesis` and `ConsensusState.update_to_state` (idempotent, so a
mid-chain param change re-registers).  An *unknown* chain_id resolves to the
ed25519 non-aggregated default, which keeps every pre-existing artifact
byte-identical: no registration, no behavior change.

Wire-side aggregated commits (types/block.AggregatedCommit) are
self-describing and verified by isinstance dispatch — a light client or
blocksync peer does not need this registry to check one.
"""

from __future__ import annotations

from dataclasses import dataclass

SCHEME_ED25519 = "ed25519"
SCHEME_BLS12381 = "bls12381"

# The timestamp every aggregated precommit signs over (unix epoch — encodes
# as an empty canonical Timestamp body).  Aggregation requires all signers
# to produce identical sign-bytes; the real timestamp travels separately as
# the commit's voting-power-weighted median.
AGG_ZERO_TS_NS = 0


@dataclass(frozen=True)
class Scheme:
    scheme: str = SCHEME_ED25519
    aggregate_commits: bool = False

    @property
    def zero_precommit_ts(self) -> bool:
        # Aggregation needs every validator to sign the *same* precommit
        # bytes, so the (per-validator) timestamp is zeroed in sign-bytes
        # and the commit carries a voting-power-weighted median instead.
        return self.aggregate_commits

    @property
    def is_default(self) -> bool:
        return self.scheme == SCHEME_ED25519 and not self.aggregate_commits


DEFAULT = Scheme()

_registry: dict = {}


def register_chain(chain_id: str, scheme) -> None:
    """Idempotent.  `scheme` is anything with .scheme / .aggregate_commits
    (crypto.schemes.Scheme or types.params.SignatureParams)."""
    sch = Scheme(scheme=scheme.scheme,
                 aggregate_commits=bool(scheme.aggregate_commits))
    if sch.is_default:
        _registry.pop(chain_id, None)
    else:
        _registry[chain_id] = sch


def for_chain(chain_id: str) -> Scheme:
    return _registry.get(chain_id, DEFAULT)


def aggregated(chain_id: str) -> bool:
    return _registry.get(chain_id, DEFAULT).aggregate_commits


def reset() -> None:
    _registry.clear()
