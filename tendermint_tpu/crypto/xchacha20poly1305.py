"""XChaCha20-Poly1305 AEAD (reference crypto/xchacha20poly1305/ — the
legacy key-file AEAD alongside xsalsa20symmetric).

Construction per draft-irtf-cfrg-xchacha: HChaCha20(key, nonce[:16])
derives a subkey, then standard ChaCha20-Poly1305 (RFC 8439, provided by
the OpenSSL-backed ``cryptography`` package) runs with the 96-bit nonce
``4x00 || nonce[16:24]``. Only HChaCha20 is hand-rolled, pinned to the
draft's §2.2.1 test vector.
"""

from __future__ import annotations

import struct
from typing import Optional

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16

_SIGMA = struct.unpack("<4I", b"expand 32-byte k")


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarter(x, a, b, c, d):
    x[a] = (x[a] + x[b]) & 0xFFFFFFFF
    x[d] = _rotl(x[d] ^ x[a], 16)
    x[c] = (x[c] + x[d]) & 0xFFFFFFFF
    x[b] = _rotl(x[b] ^ x[c], 12)
    x[a] = (x[a] + x[b]) & 0xFFFFFFFF
    x[d] = _rotl(x[d] ^ x[a], 8)
    x[c] = (x[c] + x[d]) & 0xFFFFFFFF
    x[b] = _rotl(x[b] ^ x[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """draft-irtf-cfrg-xchacha §2.2: 20 ChaCha rounds, output words
    0..3 and 12..15 (no feed-forward)."""
    x = list(_SIGMA) + list(struct.unpack("<8I", key)) \
        + list(struct.unpack("<4I", nonce16))
    for _ in range(10):
        _quarter(x, 0, 4, 8, 12)
        _quarter(x, 1, 5, 9, 13)
        _quarter(x, 2, 6, 10, 14)
        _quarter(x, 3, 7, 11, 15)
        _quarter(x, 0, 5, 10, 15)
        _quarter(x, 1, 6, 11, 12)
        _quarter(x, 2, 7, 8, 13)
        _quarter(x, 3, 4, 9, 14)
    return struct.pack("<8I", *(x[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


def _subparts(key: bytes, nonce: bytes):
    if len(key) != KEY_SIZE:
        raise ValueError("xchacha20poly1305 key must be 32 bytes")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("xchacha20poly1305 nonce must be 24 bytes")
    subkey = hchacha20(key, nonce[:16])
    return subkey, b"\x00" * 4 + nonce[16:]


def seal(key: bytes, nonce: bytes, plaintext: bytes,
         aad: bytes = b"") -> bytes:
    subkey, n12 = _subparts(key, nonce)
    return ChaCha20Poly1305(subkey).encrypt(n12, plaintext, aad or None)


def open_(key: bytes, nonce: bytes, ciphertext: bytes,
          aad: bytes = b"") -> Optional[bytes]:
    """-> plaintext, or None on authentication failure (the Go AEAD's
    Open-returns-error surface)."""
    subkey, n12 = _subparts(key, nonce)
    try:
        return ChaCha20Poly1305(subkey).decrypt(n12, ciphertext, aad or None)
    except InvalidTag:
        return None
