"""BLS12-381 extension-field tower, scalar spec (pure Python).

Layout (the standard M-twist tower, e.g. draft-irtf-cfrg-pairing-friendly):

    Fq2  = Fq [u] / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - xi),  xi = u + 1
    Fq12 = Fq6[w] / (w^2 - v)

Elements are plain tuples — Fq2 = (c0, c1) ints, Fq6 = 3-tuple of Fq2,
Fq12 = 2-tuple of Fq6 — and all ops are free functions.  This module is the
*reference semantics* for the vectorized engine in vec.py; keep it boring.
"""

from __future__ import annotations

# Field modulus p and subgroup order r (both prime); x is the BLS parameter:
#   p = (x-1)^2 (x^4 - x^2 + 1)/3 + x,   r = x^4 - x^2 + 1
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000

# The tower constants above are only honest if p/r really come from x.
assert R == X_PARAM ** 4 - X_PARAM ** 2 + 1
assert P == (X_PARAM - 1) ** 2 * R // 3 + X_PARAM

_INV2 = (P + 1) // 2  # 1/2 mod p


# --- Fq --------------------------------------------------------------------

def fq_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fq_sqrt(a: int):
    """sqrt in Fq (p = 3 mod 4), or None if a is not a QR."""
    y = pow(a, (P + 1) // 4, P)
    return y if y * y % P == a % P else None


# --- Fq2 -------------------------------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # the Fq6 non-residue, u + 1


def f2add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def f2sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def f2neg(x):
    return (-x[0] % P, -x[1] % P)


def f2conj(x):
    return (x[0], -x[1] % P)


def f2mul(x, y):
    a, b = x
    c, d = y
    ac = a * c % P
    bd = b * d % P
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def f2sqr(x):
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def f2scale(x, k):
    return (x[0] * k % P, x[1] * k % P)


def f2mul_xi(x):
    # (a + bu)(1 + u) = (a - b) + (a + b)u
    a, b = x
    return ((a - b) % P, (a + b) % P)


def f2inv(x):
    a, b = x
    d = pow(a * a + b * b, P - 2, P)
    return (a * d % P, -b * d % P)


def f2pow(x, e: int):
    r = F2_ONE
    for bit in bin(e)[2:]:
        r = f2sqr(r)
        if bit == "1":
            r = f2mul(r, x)
    return r


def f2sqrt(x):
    """sqrt in Fq2 via the norm trick, or None.  Always verified by squaring."""
    a, b = x
    if b == 0:
        s = fq_sqrt(a)
        if s is not None:
            return (s, 0)
        t = fq_sqrt(-a % P)  # (tu)^2 = -t^2 = a
        return (0, t) if t is not None else None
    s = fq_sqrt((a * a + b * b) % P)  # sqrt of the norm
    if s is None:
        return None
    d = (a + s) * _INV2 % P
    c0 = fq_sqrt(d)
    if c0 is None:
        c0 = fq_sqrt((a - s) * _INV2 % P)
        if c0 is None:
            return None
    c1 = b * pow(2 * c0 % P, P - 2, P) % P
    cand = (c0, c1)
    return cand if f2sqr(cand) == (a % P, b % P) else None


# --- Fq6 -------------------------------------------------------------------

F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6add(x, y):
    return (f2add(x[0], y[0]), f2add(x[1], y[1]), f2add(x[2], y[2]))


def f6sub(x, y):
    return (f2sub(x[0], y[0]), f2sub(x[1], y[1]), f2sub(x[2], y[2]))


def f6neg(x):
    return (f2neg(x[0]), f2neg(x[1]), f2neg(x[2]))


def f6mul_v(x):
    # (c0 + c1 v + c2 v^2) * v = xi c2 + c0 v + c1 v^2
    return (f2mul_xi(x[2]), x[0], x[1])


def f6mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = f2mul(a0, b0)
    t1 = f2mul(a1, b1)
    t2 = f2mul(a2, b2)
    c0 = f2add(t0, f2mul_xi(f2sub(f2mul(f2add(a1, a2), f2add(b1, b2)),
                                  f2add(t1, t2))))
    c1 = f2add(f2sub(f2mul(f2add(a0, a1), f2add(b0, b1)), f2add(t0, t1)),
               f2mul_xi(t2))
    c2 = f2add(f2sub(f2mul(f2add(a0, a2), f2add(b0, b2)), f2add(t0, t2)), t1)
    return (c0, c1, c2)


def f6sqr(x):
    return f6mul(x, x)


def f6inv(x):
    a0, a1, a2 = x
    c0 = f2sub(f2sqr(a0), f2mul_xi(f2mul(a1, a2)))
    c1 = f2sub(f2mul_xi(f2sqr(a2)), f2mul(a0, a1))
    c2 = f2sub(f2sqr(a1), f2mul(a0, a2))
    t = f2inv(f2add(f2mul(a0, c0),
                    f2mul_xi(f2add(f2mul(a2, c1), f2mul(a1, c2)))))
    return (f2mul(c0, t), f2mul(c1, t), f2mul(c2, t))


# --- Fq12 ------------------------------------------------------------------

F12_ONE = (F6_ONE, F6_ZERO)


def f12mul(x, y):
    a, b = x
    c, d = y
    ac = f6mul(a, c)
    bd = f6mul(b, d)
    return (f6add(ac, f6mul_v(bd)),
            f6sub(f6sub(f6mul(f6add(a, b), f6add(c, d)), ac), bd))


def f12sqr(x):
    a, b = x
    aa = f6mul(a, a)
    bb = f6mul(b, b)
    t = f6mul(a, b)
    return (f6add(aa, f6mul_v(bb)), f6add(t, t))


def f12conj(x):
    """The p^6-Frobenius — and the inverse, for cyclotomic-subgroup elements."""
    return (x[0], f6neg(x[1]))


def f12inv(x):
    a, b = x
    t = f6inv(f6sub(f6mul(a, a), f6mul_v(f6mul(b, b))))
    return (f6mul(a, t), f6neg(f6mul(b, t)))


def f12pow(x, e: int):
    r = F12_ONE
    for bit in bin(e)[2:]:
        r = f12sqr(r)
        if bit == "1":
            r = f12mul(r, x)
    return r


# Frobenius: coefficient of w^i picks up xi^(i(p-1)/6) after conjugation.
# Basis order: w^0,w^2,w^4 carry x[0]'s Fq2 coeffs, w^1,w^3,w^5 carry x[1]'s.
_FROB_BASE = f2pow(XI, (P - 1) // 6)
_FROB1 = [F2_ONE]
for _ in range(5):
    _FROB1.append(f2mul(_FROB1[-1], _FROB_BASE))


def f12_frob(x):
    (a0, a1, a2), (b0, b1, b2) = x
    return ((f2conj(a0),
             f2mul(f2conj(a1), _FROB1[2]),
             f2mul(f2conj(a2), _FROB1[4])),
            (f2mul(f2conj(b0), _FROB1[1]),
             f2mul(f2conj(b1), _FROB1[3]),
             f2mul(f2conj(b2), _FROB1[5])))


def f12_frob2(x):
    return f12_frob(f12_frob(x))
