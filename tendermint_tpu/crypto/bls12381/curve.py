"""BLS12-381 group ops: G1 over Fq, G2 over the M-twist E'/Fq2.

E : y^2 = x^3 + 4          (G1 = the order-r subgroup of E(Fq))
E': y^2 = x^3 + 4(u + 1)   (G2 = the order-r subgroup of E'(Fq2))

Points are Jacobian tuples (X, Y, Z); Z == 0 is infinity.  Serialization is
the 48/96-byte compressed form with the top-three flag bits (compressed /
infinity / y-sign), matching the layout every production BLS library uses.

Hash-to-G1 is deliberately try-and-increment (hash, check QR, clear the
cofactor) rather than RFC 9380 SSWU: this plane is a self-contained scalar
spec, not a cross-client interop surface, and the simple construction is
easier to mirror in the vectorized engine.  The DST still domain-separates
signatures from proofs of possession.
"""

from __future__ import annotations

import hashlib

from .field import (P, R, F2_ONE, F2_ZERO, f2add, f2sub, f2neg, f2mul, f2sqr,
                    f2scale, f2inv, f2sqrt, fq_sqrt)

B1 = 4
B2 = (4, 4)

# G1/G2 cofactors: |E(Fq)| = h1 * r, |E'(Fq2)| = h2 * r.
H1 = 0x396C8C005555E1568C00AAAB0000AAAB
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)

INF1 = (1, 1, 0)
INF2 = (F2_ONE, F2_ONE, F2_ZERO)


# --- G1 (plain Fq coordinates) ---------------------------------------------

def g1_is_inf(pt):
    return pt[2] == 0


def g1_double(pt):
    X, Y, Z = pt
    if Z == 0:
        return pt
    A = X * X % P
    B = Y * Y % P
    S = 4 * X * B % P
    M = 3 * A % P
    X3 = (M * M - 2 * S) % P
    Y3 = (M * (S - X3) - 8 * B * B) % P
    Z3 = 2 * Y * Z % P
    return (X3, Y3, Z3)


def g1_add(p, q):
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    H = (U2 - U1) % P
    Rr = (S2 - S1) % P
    if H == 0:
        return g1_double(p) if Rr == 0 else INF1
    HH = H * H % P
    HHH = H * HH % P
    V = U1 * HH % P
    X3 = (Rr * Rr - HHH - 2 * V) % P
    Y3 = (Rr * (V - X3) - S1 * HHH) % P
    Z3 = Z1 * Z2 * H % P
    return (X3, Y3, Z3)


def g1_neg(pt):
    return pt if pt[2] == 0 else (pt[0], -pt[1] % P, pt[2])


def g1_mul(pt, k: int):
    r = INF1
    for bit in bin(k % R if k >= R else k)[2:]:
        r = g1_double(r)
        if bit == "1":
            r = g1_add(r, pt)
    return r


def g1_to_affine(pt):
    if pt[2] == 0:
        return None
    zi = pow(pt[2], P - 2, P)
    zi2 = zi * zi % P
    return (pt[0] * zi2 % P, pt[1] * zi2 * zi % P)


def g1_on_curve(aff) -> bool:
    x, y = aff
    return (y * y - (x * x % P * x + B1)) % P == 0


def g1_in_subgroup(aff) -> bool:
    return g1_on_curve(aff) and g1_mul((aff[0], aff[1], 1), R)[2] == 0


# --- G2 (Fq2 coordinates, same formulas) -----------------------------------

def g2_is_inf(pt):
    return pt[2] == F2_ZERO


def g2_double(pt):
    X, Y, Z = pt
    if Z == F2_ZERO:
        return pt
    A = f2sqr(X)
    B = f2sqr(Y)
    S = f2scale(f2mul(X, B), 4)
    M = f2scale(A, 3)
    X3 = f2sub(f2sqr(M), f2scale(S, 2))
    Y3 = f2sub(f2mul(M, f2sub(S, X3)), f2scale(f2sqr(B), 8))
    Z3 = f2scale(f2mul(Y, Z), 2)
    return (X3, Y3, Z3)


def g2_add(p, q):
    if p[2] == F2_ZERO:
        return q
    if q[2] == F2_ZERO:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = f2sqr(Z1)
    Z2Z2 = f2sqr(Z2)
    U1 = f2mul(X1, Z2Z2)
    U2 = f2mul(X2, Z1Z1)
    S1 = f2mul(f2mul(Y1, Z2), Z2Z2)
    S2 = f2mul(f2mul(Y2, Z1), Z1Z1)
    H = f2sub(U2, U1)
    Rr = f2sub(S2, S1)
    if H == F2_ZERO:
        return g2_double(p) if Rr == F2_ZERO else INF2
    HH = f2sqr(H)
    HHH = f2mul(H, HH)
    V = f2mul(U1, HH)
    X3 = f2sub(f2sub(f2sqr(Rr), HHH), f2scale(V, 2))
    Y3 = f2sub(f2mul(Rr, f2sub(V, X3)), f2mul(S1, HHH))
    Z3 = f2mul(f2mul(Z1, Z2), H)
    return (X3, Y3, Z3)


def g2_neg(pt):
    return pt if pt[2] == F2_ZERO else (pt[0], f2neg(pt[1]), pt[2])


def g2_mul(pt, k: int):
    r = INF2
    for bit in bin(k % R if k >= R else k)[2:]:
        r = g2_double(r)
        if bit == "1":
            r = g2_add(r, pt)
    return r


def g2_to_affine(pt):
    if pt[2] == F2_ZERO:
        return None
    zi = f2inv(pt[2])
    zi2 = f2sqr(zi)
    return (f2mul(pt[0], zi2), f2mul(f2mul(pt[1], zi2), zi))


def g2_on_curve(aff) -> bool:
    x, y = aff
    return f2sub(f2sqr(y), f2add(f2mul(f2sqr(x), x), B2)) == F2_ZERO


def g2_in_subgroup(aff) -> bool:
    return g2_on_curve(aff) and g2_mul((aff[0], aff[1], F2_ONE), R)[2] == F2_ZERO


# --- compressed serialization ----------------------------------------------

_MASK381 = (1 << 381) - 1
_HALF = (P - 1) // 2


def g1_compress(aff) -> bytes:
    if aff is None:
        return bytes([0xC0]) + bytes(47)
    x, y = aff
    flags = 0x80 | (0x20 if y > _HALF else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_decompress(b: bytes):
    """48 bytes -> affine point (no subgroup check), None if malformed."""
    if len(b) != 48 or not b[0] & 0x80:
        return None
    if b[0] & 0x40:  # infinity: everything else must be zero
        if b[0] & 0x3F or any(b[1:]):
            return None
        return "inf"
    sign = (b[0] >> 5) & 1
    x = int.from_bytes(b, "big") & _MASK381
    if x >= P:
        return None
    y = fq_sqrt((x * x % P * x + B1) % P)
    if y is None:
        return None
    if (1 if y > _HALF else 0) != sign:
        y = P - y
    return (x, y)


def g2_compress(aff) -> bytes:
    if aff is None:
        return bytes([0xC0]) + bytes(95)
    (x0, x1), (y0, y1) = aff
    big = (y1 > _HALF) if y1 else (y0 > _HALF)
    flags = 0x80 | (0x20 if big else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_decompress(b: bytes):
    if len(b) != 96 or not b[0] & 0x80:
        return None
    if b[0] & 0x40:
        if b[0] & 0x3F or any(b[1:]):
            return None
        return "inf"
    sign = (b[0] >> 5) & 1
    x1 = int.from_bytes(b[:48], "big") & _MASK381
    x0 = int.from_bytes(b[48:], "big")
    if x0 >= P or x1 >= P:
        return None
    x = (x0, x1)
    y = f2sqrt(f2add(f2mul(f2sqr(x), x), B2))
    if y is None:
        return None
    big = (y[1] > _HALF) if y[1] else (y[0] > _HALF)
    if (1 if big else 0) != sign:
        y = f2neg(y)
    return (x, y)


# --- hash to G1 (try-and-increment + cofactor clearing) --------------------

_H2C_CACHE: dict = {}
_H2C_CACHE_MAX = 4096


def hash_to_g1(msg: bytes, dst: bytes):
    """Map msg -> affine G1 point.  Deterministic; memoized per (dst, msg) —
    in aggregated-commit mode every validator signs the *same* zero-timestamp
    precommit bytes, so one hash serves the whole commit."""
    key = (dst, msg)
    hit = _H2C_CACHE.get(key)
    if hit is not None:
        return hit
    base = hashlib.sha256(len(dst).to_bytes(1, "big") + dst + msg).digest()
    for ctr in range(256):
        seed = hashlib.sha256(base + bytes([ctr])).digest()
        ext = hashlib.sha256(seed + b"\x01").digest()
        x = int.from_bytes(seed + ext[:16], "big") % P
        y = fq_sqrt((x * x % P * x + B1) % P)
        if y is None:
            continue
        if ext[16] & 1:
            y = P - y
        pt = g1_mul((x, y, 1), H1)  # clear the cofactor -> lands in G1
        if pt[2] == 0:
            continue
        aff = g1_to_affine(pt)
        if len(_H2C_CACHE) >= _H2C_CACHE_MAX:
            _H2C_CACHE.clear()
        _H2C_CACHE[key] = aff
        return aff
    raise ValueError("hash_to_g1: no curve point in 256 attempts")


def reset_h2c_cache() -> None:
    _H2C_CACHE.clear()
