"""Optimal ate pairing e: G1 x G2 -> mu_r in Fq12.

Miller loop runs on the twist: the accumulator T walks multiples of Q on
E'/Fq2 in Jacobian coordinates, and each tangent/chord line is evaluated at
the twisted image of P = (xP, yP) in G1.  Every line is pre-scaled by
w^3 = v*w, an element of the Fq4 subfield — legal because the final
exponentiation kills any proper-subfield factor — which gives both line
shapes the same sparse form

    l = l00 * 1  +  l11 * (v w)  +  l12 * (v^2 w),      l0x in Fq2

so one dedicated sparse multiply serves the whole loop.  Derivation (T =
(X,Y,Z) Jacobian on E', P affine in G1, twist image of P at (xP w^2, yP w^3)):

  tangent at T, scaled by 2YZ^3 then v*w:
      l00 = 2 Y Z^3 xi yP,   l11 = 3 X^3 - 2 Y^2,   l12 = -3 X^2 Z^2 xP
  chord through T and affine Q2=(x2,y2), scaled by Z*H then v*w:
      l00 = xi Z H yP,       l11 = R x2 - Z H y2,   l12 = -R xP
  with H = x2 Z^2 - X, R = y2 Z^3 - Y.

The hard part of the final exponentiation uses the fixed-multiple identity

    3 (p^4 - p^2 + 1) / r = (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3

(asserted below with exact integers).  Computing e(.,.)^3 instead of e(.,.)
is itself a non-degenerate pairing (gcd(3, r) = 1), and every use here is a
product-of-pairings == 1 check, which the cube preserves.
"""

from __future__ import annotations

from .field import (P, R, X_PARAM, F12_ONE, f2mul, f2sqr, f2sub, f2scale,
                    f2mul_xi, f2add, f6add, f6mul_v, f12mul, f12sqr, f12conj,
                    f12inv, f12_frob, f12_frob2)
from .curve import G2_GEN, g2_neg, g2_to_affine

# the hard-part addition chain below computes exactly this exponent
assert ((X_PARAM - 1) ** 2 * (X_PARAM + P) * (X_PARAM ** 2 + P ** 2 - 1) + 3
        == 3 * ((P ** 4 - P ** 2 + 1) // R))

_ATE_BITS = bin(-X_PARAM)[3:]  # |x| MSB-first, leading bit dropped


def _sparse_f6(e, l11, l12):
    # (e0, e1, e2) * (0, l11, l12) in Fq6
    e0, e1, e2 = e
    return (f2mul_xi(f2add(f2mul(e1, l12), f2mul(e2, l11))),
            f2add(f2mul(e0, l11), f2mul_xi(f2mul(e2, l12))),
            f2add(f2mul(e0, l12), f2mul(e1, l11)))


def _sparse_mul(f, l00, l11, l12):
    # f * (a + b w), a = (l00,0,0), b = (0,l11,l12)
    A, B = f
    Aa = (f2mul(A[0], l00), f2mul(A[1], l00), f2mul(A[2], l00))
    Ba = (f2mul(B[0], l00), f2mul(B[1], l00), f2mul(B[2], l00))
    Ab = _sparse_f6(A, l11, l12)
    Bb = _sparse_f6(B, l11, l12)
    return (f6add(Aa, f6mul_v(Bb)), f6add(Ab, Ba))


def _dbl_step(f, T, xp, yp):
    X, Y, Z = T
    XX = f2sqr(X)
    YY = f2sqr(Y)
    ZZ = f2sqr(Z)
    l00 = f2mul_xi(f2scale(f2mul(Y, f2mul(Z, ZZ)), 2 * yp % P))
    l11 = f2sub(f2scale(f2mul(XX, X), 3), f2scale(YY, 2))
    l12 = f2scale(f2mul(XX, ZZ), -3 * xp % P)
    f = _sparse_mul(f, l00, l11, l12)
    S = f2scale(f2mul(X, YY), 4)
    M = f2scale(XX, 3)
    X3 = f2sub(f2sqr(M), f2scale(S, 2))
    Y3 = f2sub(f2mul(M, f2sub(S, X3)), f2scale(f2sqr(YY), 8))
    Z3 = f2scale(f2mul(Y, Z), 2)
    return f, (X3, Y3, Z3)


def _add_step(f, T, q_aff, xp, yp):
    X, Y, Z = T
    x2, y2 = q_aff
    ZZ = f2sqr(Z)
    H = f2sub(f2mul(x2, ZZ), X)
    Rr = f2sub(f2mul(y2, f2mul(Z, ZZ)), Y)
    ZH = f2mul(Z, H)
    l00 = f2mul_xi(f2scale(ZH, yp))
    l11 = f2sub(f2mul(Rr, x2), f2mul(ZH, y2))
    l12 = f2scale(Rr, -xp % P)
    f = _sparse_mul(f, l00, l11, l12)
    HH = f2sqr(H)
    HHH = f2mul(H, HH)
    V = f2mul(X, HH)
    X3 = f2sub(f2sub(f2sqr(Rr), HHH), f2scale(V, 2))
    Y3 = f2sub(f2mul(Rr, f2sub(V, X3)), f2mul(Y, HHH))
    return f, (X3, Y3, ZH)


def miller_loop(p_aff, q_aff):
    """f_{|x|,Q}(P), conjugated for x < 0.  Both points affine, non-infinite."""
    xp, yp = p_aff
    T = (q_aff[0], q_aff[1], (1, 0))
    f = F12_ONE
    for bit in _ATE_BITS:
        f = f12sqr(f)
        f, T = _dbl_step(f, T, xp, yp)
        if bit == "1":
            f, T = _add_step(f, T, q_aff, xp, yp)
    return f12conj(f)


def _cyc_pow_x(m):
    """m^x for cyclotomic m (x is negative: conjugate of m^|x|)."""
    r = m
    for bit in _ATE_BITS:
        r = f12sqr(r)
        if bit == "1":
            r = f12mul(r, m)
    return f12conj(r)


def final_exp(f):
    # easy part: f^((p^6-1)(p^2+1)) — lands in the cyclotomic subgroup
    f = f12mul(f12conj(f), f12inv(f))
    f = f12mul(f12_frob2(f), f)
    # hard part, exponent 3(p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3
    m = f
    t = f12mul(_cyc_pow_x(m), f12conj(m))            # m^(x-1)
    a = f12mul(_cyc_pow_x(t), f12conj(t))            # m^((x-1)^2)
    b = f12mul(_cyc_pow_x(a), f12_frob(a))           # a^(x+p)
    c = f12mul(f12mul(_cyc_pow_x(_cyc_pow_x(b)),     # b^(x^2+p^2-1)
                      f12_frob2(b)), f12conj(b))
    return f12mul(c, f12mul(f12sqr(m), m))           # * m^3


def pairing(p_aff, q_aff):
    return final_exp(miller_loop(p_aff, q_aff))


def multi_pairing_check(pairs) -> bool:
    """prod e(Pi, Qi) == 1?  One shared final exponentiation; pairs with an
    infinite point contribute the identity and are skipped."""
    f = F12_ONE
    for p_aff, q_aff in pairs:
        if p_aff is None or q_aff is None:
            continue
        f = f12mul(f, miller_loop(p_aff, q_aff))
    return final_exp(f) == F12_ONE


NEG_G2_AFF = g2_to_affine(g2_neg((G2_GEN[0], G2_GEN[1], (1, 0))))
