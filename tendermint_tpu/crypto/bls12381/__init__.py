"""min-sig BLS12-381 signatures: sigs in G1 (48 B), pubkeys in G2 (96 B).

    sk in Z_r,  pk = sk * g2,  sig = sk * H(m) in G1
    verify:  e(sig, -g2) * e(H(m), pk) == 1
    fast_aggregate_verify(pks, m, asig):  e(asig, -g2) * e(H(m), apk) == 1

Aggregation over one message is only sound against rogue-key attacks when
every pubkey has proven possession of its secret key, so key *registration*
(genesis validation / validator updates) demands a proof-of-possession — a
BLS signature over the pubkey bytes under a dedicated DST — and the
consensus plane refuses unregistered BLS validator keys.  Verification
itself does not re-check PoP: by then the key is already committed to a
validator-set hash that registration vetted.

Decompression + subgroup checks are memoized per byte-string (subgroup
check = one scalar mul by r, the dominant cost), as is the aggregate
pubkey per signer-set.  `reset()` drops every cache; the test harness calls
it between tests.

Known limitation: the pure-Python double-and-add in curve.g1_mul/g2_mul is
VARIABLE-TIME in the scalar — signing leaks timing about the secret key.
That is acceptable for this scalar spec plane (tests, benches, in-proc
nets) but rules out the pure-Python signer for keys that face untrusted
network observers; a production deployment wants a constant-time native
backend behind the same sign/verify surface.
"""

from __future__ import annotations

import hashlib

from . import curve as _c
from . import pairing as _p
from .field import R

DST_SIG = b"TMTPU-BLS12381-SIG-"
DST_POP = b"TMTPU-BLS12381-POP-"

PUBKEY_SIZE = 96
SIG_SIZE = 48

_g1_cache: dict = {}   # sig bytes -> affine G1 point | None
_g2_cache: dict = {}   # pk bytes -> affine G2 point | None
_apk_cache: dict = {}  # tuple(pk bytes) -> affine G2 aggregate | None
_pop_registered: set = set()
_CACHE_MAX = 8192


def reset() -> None:
    _g1_cache.clear()
    _g2_cache.clear()
    _apk_cache.clear()
    _pop_registered.clear()
    _c.reset_h2c_cache()


def _bound(cache: dict) -> None:
    if len(cache) >= _CACHE_MAX:
        cache.clear()


# --- keys ------------------------------------------------------------------

def sk_from_seed(seed: bytes) -> int:
    sk = int.from_bytes(hashlib.sha256(b"tmtpu-bls-keygen" + seed).digest()
                        + hashlib.sha256(b"tmtpu-bls-keygen2" + seed).digest(),
                        "big") % R
    return sk or 1


def sk_to_bytes(sk: int) -> bytes:
    return sk.to_bytes(32, "big")


def sk_from_bytes(b: bytes) -> int:
    if len(b) != 32:
        raise ValueError(f"BLS secret key must be 32 bytes, got {len(b)}")
    sk = int.from_bytes(b, "big") % R
    if sk == 0:
        raise ValueError("BLS secret key is zero")
    return sk


def sk_to_pk(sk: int) -> bytes:
    return _c.g2_compress(_c.g2_to_affine(_c.g2_mul(
        (_c.G2_GEN[0], _c.G2_GEN[1], (1, 0)), sk)))


def decompress_pubkey(pk: bytes):
    """pk bytes -> affine G2 point, or None (malformed / infinity / outside
    the r-subgroup).  Memoized."""
    if pk in _g2_cache:
        return _g2_cache[pk]
    aff = _c.g2_decompress(pk)
    if aff == "inf" or (aff is not None and not _c.g2_in_subgroup(aff)):
        aff = None
    _bound(_g2_cache)
    _g2_cache[pk] = aff
    return aff


def _decompress_sig(sig: bytes):
    if sig in _g1_cache:
        return _g1_cache[sig]
    aff = _c.g1_decompress(sig)
    if aff == "inf" or (aff is not None and not _c.g1_in_subgroup(aff)):
        aff = None
    _bound(_g1_cache)
    _g1_cache[sig] = aff
    return aff


# --- sign / verify ---------------------------------------------------------

def sign(sk: int, msg: bytes, dst: bytes = DST_SIG) -> bytes:
    h = _c.hash_to_g1(msg, dst)
    return _c.g1_compress(_c.g1_to_affine(_c.g1_mul((h[0], h[1], 1), sk)))


def verify(pk: bytes, msg: bytes, sig: bytes, dst: bytes = DST_SIG) -> bool:
    q = decompress_pubkey(pk)
    s = _decompress_sig(sig)
    if q is None or s is None:
        return False
    return _p.multi_pairing_check([(s, _p.NEG_G2_AFF),
                                   (_c.hash_to_g1(msg, dst), q)])


def aggregate(sigs) -> bytes:
    """Sum of G1 signatures.  Raises on a malformed input signature."""
    acc = _c.INF1
    for sig in sigs:
        s = _decompress_sig(sig)
        if s is None:
            raise ValueError("aggregate: invalid BLS signature input")
        acc = _c.g1_add(acc, (s[0], s[1], 1))
    return _c.g1_compress(_c.g1_to_affine(acc))


def aggregate_pubkeys(pks):
    key = tuple(pks)
    if key in _apk_cache:
        return _apk_cache[key]
    acc = _c.INF2
    ok = True
    for pk in pks:
        q = decompress_pubkey(pk)
        if q is None:
            ok = False
            break
        acc = _c.g2_add(acc, (q[0], q[1], (1, 0)))
    apk = _c.g2_to_affine(acc) if ok and acc[2] != (0, 0) else None
    _bound(_apk_cache)
    _apk_cache[key] = apk
    return apk


def fast_aggregate_verify(pks, msg: bytes, sig: bytes,
                          dst: bytes = DST_SIG) -> bool:
    """All of `pks` signed the same msg; `sig` is the aggregate."""
    if not pks:
        return False
    apk = aggregate_pubkeys(pks)
    s = _decompress_sig(sig)
    if apk is None or s is None:
        return False
    return _p.multi_pairing_check([(s, _p.NEG_G2_AFF),
                                   (_c.hash_to_g1(msg, dst), apk)])


# --- proof of possession ---------------------------------------------------

def pop_prove(sk: int) -> bytes:
    return sign(sk, sk_to_pk(sk), dst=DST_POP)


def pop_verify(pk: bytes, pop: bytes) -> bool:
    return verify(pk, pk, pop, dst=DST_POP)


def register_key(pk: bytes, pop: bytes) -> None:
    """Admit a BLS pubkey into the aggregation-eligible set.  Raises unless
    the proof of possession verifies — this is the rogue-key gate."""
    if pk in _pop_registered:
        return
    if not pop_verify(pk, pop):
        raise ValueError("BLS proof-of-possession verification failed")
    _pop_registered.add(pk)


def is_registered(pk: bytes) -> bool:
    return pk in _pop_registered
