"""Vectorized BLS12-381 batch engine: lane-parallel Fq limb arithmetic.

The one genuinely data-parallel op in the aggregated-commit plane is the
aggregate-pubkey sum (many G2 points, one result), so that is what this
engine vectorizes: field elements become limb lanes in Montgomery form
(CIOS reduction, canonical < p after every op so lane equality tests are
exact), points become lane arrays, and the sum is a pad-to-power-of-two
Jacobian tree reduction whose pairwise-add round is one vectorized kernel.

Limb geometry is per backend: numpy runs 15x26-bit limbs in int64; the jax
variant runs 30x13-bit limbs in int32 because the device plane (like the
ed25519 kernels) stays inside 32-bit integers — column sums of 30 products
of 2^26 peak at 30*2^26 < 2^31.  R = 2^390 for both, so the Montgomery
constants are shared.

Routing mirrors crypto/batch.py exactly: the device attempt sits behind
`device_breaker`, raises through the armed `crypto.bls_verify` fault site,
records a phase Segment per dispatch, and on ANY failure re-runs on the
host scalar path with byte-identical verdicts while the breaker counts the
strike.  Backend selection: TMTPU_BLS_BACKEND = scalar (default) | numpy |
jax;  TMTPU_BLS_JIT=0 runs the jax backend eagerly (debug only — per-op
dispatch makes it orders of magnitude slower than the jitted rounds).

Honesty note (measured on this host, CPU XLA): per-op dispatch overhead
makes both vector backends *slower* than the scalar Python path at every
realistic validator count — they exist as the device on-ramp and are gated
off by default; `bench.py --config aggsig` reports the scalar numbers.
"""

from __future__ import annotations

import os

import numpy as np

from ...libs.faults import faults
from .. import phases as _phases
from ..breaker import classify_device_error, device_breaker
from . import DST_SIG, decompress_pubkey
from .curve import g2_to_affine, hash_to_g1
from .field import P
from .pairing import NEG_G2_AFF, multi_pairing_check

R_BITS = 390
R_MONT = pow(2, R_BITS, P)
R2 = pow(2, 2 * R_BITS, P)
NPRIME = (-pow(P, -1, 1 << R_BITS)) % (1 << R_BITS)

FAULT_SITE = "crypto.bls_verify"

stats = {"device_calls": 0, "host_vec_calls": 0, "scalar_calls": 0,
         "device_errors": 0, "breaker_rejections": 0}


def reset_stats() -> None:
    for k in stats:
        stats[k] = 0


class LimbCfg:
    """One limb geometry: `nlimbs` limbs of `limb` bits in `dtype` lanes."""

    def __init__(self, nlimbs: int, limb: int, dtype):
        assert nlimbs * limb == R_BITS
        self.nlimbs = nlimbs
        self.limb = limb
        self.mask = (1 << limb) - 1
        self.dtype = dtype
        self.p_limbs = self.to_limbs_np(P)
        self.nprime_limbs = self.to_limbs_np(NPRIME)
        self.r2_limbs = self.to_limbs_np(R2)

    def to_limbs_np(self, x: int) -> np.ndarray:
        return np.array([(x >> (self.limb * i)) & self.mask
                         for i in range(self.nlimbs)], dtype=self.dtype)


CFG_NP = LimbCfg(15, 26, np.int64)   # products 2^52, sums < 2^56 in int64
CFG_JAX = LimbCfg(30, 13, np.int32)  # products 2^26, sums < 2^31 in int32


def _cfg_for(backend: str) -> LimbCfg:
    return CFG_JAX if backend == "jax" else CFG_NP


def _get_xp(backend: str):
    if backend == "jax":
        import jax.numpy as jnp

        return jnp
    return np


def _acc(xp, arr, sl, val):
    if xp is np:
        arr[sl] += val
        return arr
    return arr.at[sl].add(val)


def _setrow(xp, arr, i, val):
    if xp is np:
        arr[i] = val
        return arr
    return arr.at[i].set(val)


# --- limb vectors: shape (nlimbs, n), canonical (< p), Montgomery form -----

def int_to_vl(xp, cfg, values):
    out = np.zeros((cfg.nlimbs, len(values)), dtype=cfg.dtype)
    for j, v in enumerate(values):
        for i in range(cfg.nlimbs):
            out[i, j] = (v >> (cfg.limb * i)) & cfg.mask
    return out if xp is np else xp.asarray(out)


def vl_to_int(cfg, limbs) -> list:
    a = np.asarray(limbs)
    return [sum(int(a[i, j]) << (cfg.limb * i) for i in range(cfg.nlimbs)) % P
            for j in range(a.shape[1])]


def _carry(xp, cfg, cols):
    rows = cols.shape[0]
    for i in range(rows - 1):
        c = cols[i] >> cfg.limb  # arithmetic shift: floors negatives too
        cols = _setrow(xp, cols, i, cols[i] - (c << cfg.limb))
        cols = _acc(xp, cols, i + 1, c)
    return cols


def _cond_sub_p(xp, cfg, r):
    """r < 2p, carried -> canonical r mod p (lane-wise select)."""
    pl = cfg.p_limbs[:, None] if xp is np else xp.asarray(cfg.p_limbs)[:, None]
    d = _carry(xp, cfg, r - pl)
    neg = d[cfg.nlimbs - 1] < 0
    return xp.where(neg[None, :], r, d)


def mont_mul(xp, cfg, a, b):
    n = a.shape[1]
    nl = cfg.nlimbs
    pl = cfg.p_limbs if xp is np else xp.asarray(cfg.p_limbs)
    npr = cfg.nprime_limbs if xp is np else xp.asarray(cfg.nprime_limbs)
    cols = xp.zeros((2 * nl + 1, n), dtype=cfg.dtype)
    for i in range(nl):
        cols = _acc(xp, cols, slice(i, i + nl), a[i] * b)
    cols = _carry(xp, cfg, cols)
    tlo = cols[:nl]
    mcols = xp.zeros((nl, n), dtype=cfg.dtype)
    for i in range(nl):
        mcols = _acc(xp, mcols, slice(i, nl), tlo[i] * npr[:nl - i, None])
    # carry mod 2^390: the top carry drops
    for i in range(nl - 1):
        c = mcols[i] >> cfg.limb
        mcols = _setrow(xp, mcols, i, mcols[i] - (c << cfg.limb))
        mcols = _acc(xp, mcols, i + 1, c)
    mcols = _setrow(xp, mcols, nl - 1, mcols[nl - 1] & cfg.mask)
    for i in range(nl):
        cols = _acc(xp, cols, slice(i, i + nl), mcols[i] * pl[:, None])
    cols = _carry(xp, cfg, cols)
    return _cond_sub_p(xp, cfg, cols[nl:2 * nl])


def vl_add(xp, cfg, a, b):
    return _cond_sub_p(xp, cfg, _carry(xp, cfg, a + b))


def vl_sub(xp, cfg, a, b):
    pl = cfg.p_limbs[:, None] if xp is np else xp.asarray(cfg.p_limbs)[:, None]
    d = _carry(xp, cfg, a - b)
    neg = d[cfg.nlimbs - 1] < 0
    d2 = _carry(xp, cfg, d + pl)
    return xp.where(neg[None, :], d2, d)


def to_mont(xp, cfg, a):
    r2 = cfg.r2_limbs[:, None] if xp is np else xp.asarray(cfg.r2_limbs)[:, None]
    return mont_mul(xp, cfg, a, r2 * xp.ones((1, a.shape[1]), dtype=cfg.dtype))


def from_mont(xp, cfg, a):
    one = xp.zeros_like(a)
    one = _setrow(xp, one, 0, one[0] + 1)
    return mont_mul(xp, cfg, a, one)


# --- Fq2 / G2 lanes --------------------------------------------------------
# Fq2 element = (c0, c1) limb arrays; point = (X, Y, Z) of Fq2.

def _f2mul(xp, cfg, x, y):
    a, b = x
    c, d = y
    ac = mont_mul(xp, cfg, a, c)
    bd = mont_mul(xp, cfg, b, d)
    cross = mont_mul(xp, cfg, vl_add(xp, cfg, a, b), vl_add(xp, cfg, c, d))
    return (vl_sub(xp, cfg, ac, bd),
            vl_sub(xp, cfg, vl_sub(xp, cfg, cross, ac), bd))


def _f2sqr(xp, cfg, x):
    return _f2mul(xp, cfg, x, x)


def _f2add(xp, cfg, x, y):
    return (vl_add(xp, cfg, x[0], y[0]), vl_add(xp, cfg, x[1], y[1]))


def _f2sub(xp, cfg, x, y):
    return (vl_sub(xp, cfg, x[0], y[0]), vl_sub(xp, cfg, x[1], y[1]))


def _f2dbl(xp, cfg, x):
    return _f2add(xp, cfg, x, x)


def _f2zero_mask(xp, x):
    return xp.all(x[0] == 0, axis=0) & xp.all(x[1] == 0, axis=0)


def _f2where(xp, cond, x, y):
    c = cond[None, :]
    return (xp.where(c, x[0], y[0]), xp.where(c, x[1], y[1]))


def g2_add_vec(xp, cfg, p, q):
    """Lane-wise complete Jacobian addition on E'/Fq2 (Montgomery limbs).
    Handles infinity lanes (Z == 0), doubling lanes, and P == -Q lanes."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = _f2sqr(xp, cfg, Z1)
    Z2Z2 = _f2sqr(xp, cfg, Z2)
    U1 = _f2mul(xp, cfg, X1, Z2Z2)
    U2 = _f2mul(xp, cfg, X2, Z1Z1)
    S1 = _f2mul(xp, cfg, _f2mul(xp, cfg, Y1, Z2), Z2Z2)
    S2 = _f2mul(xp, cfg, _f2mul(xp, cfg, Y2, Z1), Z1Z1)
    H = _f2sub(xp, cfg, U2, U1)
    Rr = _f2sub(xp, cfg, S2, S1)
    HH = _f2sqr(xp, cfg, H)
    HHH = _f2mul(xp, cfg, H, HH)
    V = _f2mul(xp, cfg, U1, HH)
    X3 = _f2sub(xp, cfg, _f2sub(xp, cfg, _f2sqr(xp, cfg, Rr), HHH),
                _f2dbl(xp, cfg, V))
    Y3 = _f2sub(xp, cfg, _f2mul(xp, cfg, Rr, _f2sub(xp, cfg, V, X3)),
                _f2mul(xp, cfg, S1, HHH))
    Z3 = _f2mul(xp, cfg, _f2mul(xp, cfg, Z1, Z2), H)

    # doubling lanes (H == 0, R == 0)
    A = _f2sqr(xp, cfg, X1)
    B = _f2sqr(xp, cfg, Y1)
    S = _f2dbl(xp, cfg, _f2dbl(xp, cfg, _f2mul(xp, cfg, X1, B)))
    M = _f2add(xp, cfg, _f2dbl(xp, cfg, A), A)
    Xd = _f2sub(xp, cfg, _f2sqr(xp, cfg, M), _f2dbl(xp, cfg, S))
    B2 = _f2sqr(xp, cfg, B)
    B8 = _f2dbl(xp, cfg, _f2dbl(xp, cfg, _f2dbl(xp, cfg, B2)))
    Yd = _f2sub(xp, cfg, _f2mul(xp, cfg, M, _f2sub(xp, cfg, S, Xd)), B8)
    Zd = _f2dbl(xp, cfg, _f2mul(xp, cfg, Y1, Z1))

    p_inf = _f2zero_mask(xp, Z1)
    q_inf = _f2zero_mask(xp, Z2)
    h_zero = _f2zero_mask(xp, H)
    r_zero = _f2zero_mask(xp, Rr)
    both = (~p_inf) & (~q_inf)
    dbl = both & h_zero & r_zero
    cancel = both & h_zero & (~r_zero)

    X3 = _f2where(xp, dbl, Xd, X3)
    Y3 = _f2where(xp, dbl, Yd, Y3)
    Z3 = _f2where(xp, dbl, Zd, Z3)
    zero = (xp.zeros_like(Z3[0]), xp.zeros_like(Z3[1]))
    Z3 = _f2where(xp, cancel, zero, Z3)
    X3 = _f2where(xp, q_inf, X1, X3)
    Y3 = _f2where(xp, q_inf, Y1, Y3)
    Z3 = _f2where(xp, q_inf, Z1, Z3)
    X3 = _f2where(xp, p_inf, X2, X3)
    Y3 = _f2where(xp, p_inf, Y2, Y3)
    Z3 = _f2where(xp, p_inf, Z2, Z3)
    return (X3, Y3, Z3)


_jit_add_cache: dict = {}


def _g2_add_round(backend: str, p, q, jit: bool):
    if backend == "jax" and jit:
        import jax

        lanes = int(np.asarray(p[0][0]).shape[1])
        fn = _jit_add_cache.get(lanes)
        if fn is None:
            import jax.numpy as jnp

            fn = jax.jit(lambda a, b: g2_add_vec(jnp, CFG_JAX, a, b))
            _jit_add_cache[lanes] = fn
        return fn(p, q)
    return g2_add_vec(_get_xp(backend), _cfg_for(backend), p, q)


def _points_to_lanes(xp, cfg, affs):
    """Affine int points -> Montgomery limb lanes, padded to a power of 2
    with infinity lanes."""
    n = len(affs)
    lanes = 1
    while lanes < n:
        lanes *= 2
    pad = lanes - n
    xs0 = [a[0][0] for a in affs] + [0] * pad
    xs1 = [a[0][1] for a in affs] + [0] * pad
    ys0 = [a[1][0] for a in affs] + [0] * pad
    ys1 = [a[1][1] for a in affs] + [0] * pad
    zs0 = [1] * n + [0] * pad
    zs1 = [0] * lanes

    def mk(vals):
        return to_mont(xp, cfg, int_to_vl(xp, cfg, vals))

    return ((mk(xs0), mk(xs1)), (mk(ys0), mk(ys1)), (mk(zs0), mk(zs1)))


def aggregate_pubkeys_vec(pks, backend: str = "numpy", jit: bool = True):
    """Sum the (decompressed, subgroup-checked) pubkeys with the lane engine.
    Returns the affine aggregate, or None on any invalid key / zero sum."""
    affs = []
    for pk in pks:
        q = decompress_pubkey(pk)
        if q is None:
            return None
        affs.append(q)
    if not affs:
        return None
    if len(affs) == 1:
        if backend == "jax":
            # still produce real device evidence (a breaker half-open probe
            # must not re-close on work that never touched the device): one
            # Montgomery roundtrip of the x-coordinate through device limbs
            xp = _get_xp(backend)
            cfg = _cfg_for(backend)
            x0 = affs[0][0][0]
            rt = vl_to_int(cfg, from_mont(xp, cfg, to_mont(
                xp, cfg, int_to_vl(xp, cfg, [x0]))))[0]
            if rt != x0:
                raise RuntimeError("bls device limb roundtrip mismatch")
        return affs[0]
    xp = _get_xp(backend)
    cfg = _cfg_for(backend)
    pt = _points_to_lanes(xp, cfg, affs)
    lanes = int(np.asarray(pt[0][0]).shape[1])
    while lanes > 1:
        half = lanes // 2
        left = tuple(tuple(c[:, :half] for c in comp) for comp in pt)
        right = tuple(tuple(c[:, half:] for c in comp) for comp in pt)
        pt = _g2_add_round(backend, left, right, jit)
        lanes = half
    X, Y, Z = [tuple(vl_to_int(cfg, from_mont(xp, cfg, c))[0] for c in comp)
               for comp in pt]
    if Z == (0, 0):
        return None
    return g2_to_affine((X, Y, Z))


# --- routed fast-aggregate-verify (the consensus-plane entry point) --------

def backend_from_env() -> str:
    b = os.environ.get("TMTPU_BLS_BACKEND", "scalar").strip().lower()
    return b if b in ("scalar", "numpy", "jax") else "scalar"


def _pairing_verdict(apk, msg: bytes, sig: bytes, dst: bytes) -> bool:
    from . import _decompress_sig

    s = _decompress_sig(sig)
    if apk is None or s is None:
        return False
    return multi_pairing_check([(s, NEG_G2_AFF), (hash_to_g1(msg, dst), apk)])


def fast_aggregate_verify_routed(pks, msg: bytes, sig: bytes,
                                 dst: bytes = DST_SIG,
                                 backend=None, mode: str = "full") -> bool:
    """fast_aggregate_verify with backend routing.  The jax backend is the
    device path: breaker-gated, chaos-injectable at `crypto.bls_verify`,
    phase-recorded; any failure falls back to the host scalar engine with
    an identical verdict.

    ``mode`` labels which verify_commit* entry point asked (full / light /
    trusting) — it never changes the verdict, only the telemetry: the call
    is timed into ``crypto_pairing_seconds{plane}``, counted into
    ``crypto_aggregate_verify_total{scheme,mode}``, and wrapped in a
    height-tagged ``agg_verify`` tracer span so trace_merge/stage
    breakdowns can split ed25519 vs bls12381 commits."""
    import time as _time

    from ...libs.trace import tracer

    plane, height = _phases.context()
    span_args = {"scheme": "bls12381", "mode": mode, "n_signers": len(pks)}
    if height is not None:
        span_args["height"] = height
    t0 = _time.perf_counter()
    try:
        with tracer.span("agg_verify", **span_args):
            return _routed(pks, msg, sig, dst, backend)
    finally:
        m = _phases.metrics
        if m is not None:
            try:
                m.pairing_seconds.labels(plane or "aggsig").observe(
                    _time.perf_counter() - t0)
                m.aggregate_verify_total.labels("bls12381", mode).inc()
            except Exception:
                pass


def _routed(pks, msg: bytes, sig: bytes, dst: bytes, backend) -> bool:
    from . import fast_aggregate_verify  # scalar reference path

    if backend is None:
        backend = backend_from_env()
    if not pks:
        return False
    if backend == "jax" and not device_breaker.allow():
        stats["breaker_rejections"] += 1
        backend = "scalar"
    if backend == "jax":
        jit = os.environ.get("TMTPU_BLS_JIT", "1") != "0"
        n = len(pks)
        rec = _phases.Segment(sigs=n, chunk=n, device="bls-apk",
                              plane="aggsig")
        try:
            faults.inject(FAULT_SITE)
            rec.begin().pack_done()
            apk = aggregate_pubkeys_vec(pks, backend="jax", jit=jit)
            rec.dispatched().fetched()
            stats["device_calls"] += 1
            device_breaker.record_success()
        except Exception as e:
            rec.abandon()
            classify_device_error(e)  # normalizes the strike class for logs
            device_breaker.record_failure()
            stats["device_errors"] += 1
            _phases.count_host("aggsig", n)
            return fast_aggregate_verify(pks, msg, sig, dst=dst)
        return _pairing_verdict(apk, msg, sig, dst)
    if backend == "numpy":
        stats["host_vec_calls"] += 1
        return _pairing_verdict(aggregate_pubkeys_vec(pks, backend="numpy"),
                                msg, sig, dst)
    stats["scalar_calls"] += 1
    return fast_aggregate_verify(pks, msg, sig, dst=dst)


def _self_check(n: int = 5) -> bool:
    """numpy lane engine agrees with the scalar spec on an n-key aggregate."""
    from . import aggregate_pubkeys, sk_from_seed, sk_to_pk

    pks = [sk_to_pk(sk_from_seed(bytes([i]) * 4)) for i in range(1, n + 1)]
    return aggregate_pubkeys(pks) == aggregate_pubkeys_vec(pks,
                                                           backend="numpy")
