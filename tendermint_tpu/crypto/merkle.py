"""RFC 6962 merkle trees + proofs (reference crypto/merkle/{tree,proof}.go).

Domain-separated hashing: leaf = SHA256(0x00 || item), inner = SHA256(0x01 || l || r).
Empty tree hashes to SHA256(""). Split point is the largest power of two < n
(reference crypto/merkle/tree.go:85 getSplitPoint).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"

# Bound on proof depth, as in the reference (crypto/merkle/proof.go:14
# MaxAunts=100): rejects adversarial proofs instead of recursing unboundedly.
MAX_AUNTS = 100

# Pre-seeded hash objects: copying a seeded sha256 state is cheaper than
# re-hashing the domain prefix for every node, and update(l); update(r)
# avoids materializing the prefix||l||r concatenation per inner node.
_LEAF_SEED = hashlib.sha256(LEAF_PREFIX)
_INNER_SEED = hashlib.sha256(INNER_PREFIX)


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(item: bytes) -> bytes:
    h = _LEAF_SEED.copy()
    h.update(item)
    return h.digest()


def inner_hash(left: bytes, right: bytes) -> bytes:
    h = _INNER_SEED.copy()
    h.update(left)
    h.update(right)
    return h.digest()


def _split_point(n: int) -> int:
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root (reference crypto/merkle/tree.go:9).

    Iterative bottom-up pass over a level buffer instead of the reference's
    recursion. The reference tree splits at the largest power of two < n;
    that tree is identical to pairing adjacent nodes level by level and
    promoting an unpaired last node unchanged (the odd node joins exactly at
    the level where everything to its left is a full power-of-two subtree),
    so the roots are byte-identical while per-node Python call overhead —
    dominant at 1000+ leaf valset/commit hashing scale — disappears.
    """
    n = len(items)
    if n == 0:
        return _sha256(b"")
    leaf_seed = _LEAF_SEED
    level: List[bytes] = []
    for item in items:
        h = leaf_seed.copy()
        h.update(item)
        level.append(h.digest())
    inner_seed = _INNER_SEED
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            h = inner_seed.copy()
            h.update(level[i])
            h.update(level[i + 1])
            nxt.append(h.digest())
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


# --- vectorized fast path + incremental roots (app-hash merkle) -------------
#
# MerkleKVStoreApplication recomputes its root every commit; at real state
# sizes that is the execution plane's commit bottleneck. Two escapes, both
# byte-identical to hash_from_byte_slices (differential-tested):
#
#  * batched hashing — one tree level's nodes are equal-length messages
#    (inner nodes always 65 bytes), so they vectorize through
#    crypto/merkle_fast.py: numpy on the host, jnp on the device behind
#    the shared crypto breaker with hashlib as the always-on fallback.
#  * IncrementalMerkle — a cached level structure patched along the paths
#    of dirty leaves, so commit cost scales with writes, not state size.
#
# TMTPU_MERKLE_FAST=0 disables batching (hashlib everywhere);
# TMTPU_MERKLE_NP_MIN / TMTPU_MERKLE_DEVICE_MIN set the minimum batch for
# the numpy / device routes (numpy per-op overhead beats hashlib's C loop
# only for wide levels; the device needs wider still to amortize dispatch).

import os as _os


def _env_int(name: str, default: int) -> int:
    try:
        return int(_os.environ.get(name, default))
    except ValueError:
        return default


def _batch_sha256(msgs: List[bytes]) -> Optional[List[bytes]]:
    """Hash n equal-length messages, vectorized when worthwhile; None
    tells the caller to take the hashlib loop."""
    if (_os.environ.get("TMTPU_MERKLE_FAST", "1") == "0"
            or len(msgs) < _env_int("TMTPU_MERKLE_NP_MIN", 1024)):
        return None
    try:
        from . import merkle_fast as mf
    except Exception:
        return None
    if len(msgs) >= _env_int("TMTPU_MERKLE_DEVICE_MIN", 16384):
        from .breaker import device_breaker

        if mf.device_ready() and device_breaker.allow():
            try:
                out = mf.sha256_many_device(msgs)
                device_breaker.record_success()
                return out
            except Exception:
                device_breaker.record_failure()
    return mf.sha256_many_np(msgs)


def _leaf_hashes(items: Sequence[bytes]) -> List[bytes]:
    """Leaf level, batched per item length (one kvstore level is mostly
    homogeneous; stragglers take the hashlib path)."""
    out: List[Optional[bytes]] = [None] * len(items)
    by_len: dict = {}
    for i, item in enumerate(items):
        by_len.setdefault(len(item), []).append(i)
    for idxs in by_len.values():
        hashed = _batch_sha256([LEAF_PREFIX + items[i] for i in idxs])
        if hashed is None:
            for i in idxs:
                out[i] = leaf_hash(items[i])
        else:
            for j, i in enumerate(idxs):
                out[i] = hashed[j]
    return out


def _inner_level(level: List[bytes]) -> List[bytes]:
    """One reduction step: pair-adjacent inner hashes, odd node promoted
    (same scheme as hash_from_byte_slices)."""
    pairs = [INNER_PREFIX + level[2 * i] + level[2 * i + 1]
             for i in range(len(level) // 2)]
    nxt = _batch_sha256(pairs)
    if nxt is None:
        nxt = [inner_hash(level[2 * i], level[2 * i + 1])
               for i in range(len(level) // 2)]
    if len(level) % 2:
        nxt.append(level[-1])
    return nxt


def _build_levels(items: Sequence[bytes]) -> List[List[bytes]]:
    """All tree levels bottom-up; levels[0] = leaf hashes, levels[-1] =
    [root]. Empty input is the caller's problem (no levels exist)."""
    levels = [_leaf_hashes(items)]
    while len(levels[-1]) > 1:
        levels.append(_inner_level(levels[-1]))
    return levels


def fast_hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """hash_from_byte_slices through the vectorized path — byte-identical
    root, batched level hashing when the tree is wide enough."""
    if len(items) == 0:
        return _sha256(b"")
    return _build_levels(list(items))[-1][0]


class IncrementalMerkle:
    """Merkle root cache with dirty-leaf patching.

    ``root(keys, leaf_item, dirty)`` returns the root over
    ``[leaf_item(k) for k in keys]``. While ``keys`` is unchanged from the
    previous call, only leaves named in ``dirty`` re-hash and only their
    root paths recompute — O(|dirty| · log n) instead of O(n). Any key-set
    change (insert/delete/reorder), ``dirty=None``, or a wide dirty set
    triggers a full (vectorized) rebuild. The result is always identical
    to ``hash_from_byte_slices`` over the same items; tests hold the two
    in lockstep over randomized op sequences.
    """

    def __init__(self):
        self._keys: List = []
        self._pos: dict = {}
        self._levels: Optional[List[List[bytes]]] = None
        self.rebuilds = 0  # observability for tests/bench
        self.patches = 0

    def reset(self) -> None:
        self._keys, self._pos, self._levels = [], {}, None

    def root(self, keys: Sequence, leaf_item, dirty=None) -> bytes:
        keys = list(keys)
        if not keys:
            self.reset()
            return _sha256(b"")
        stale = (self._levels is None or dirty is None
                 or keys != self._keys)
        # patching is hashlib-serial; past ~n/4 dirty leaves the batched
        # full rebuild is cheaper and touches every path anyway
        if not stale and len(dirty) >= max(32, len(keys) // 4):
            stale = True
        if stale:
            self._keys = keys
            self._pos = {k: i for i, k in enumerate(keys)}
            self._levels = _build_levels([leaf_item(k) for k in keys])
            self.rebuilds += 1
            return self._levels[-1][0]
        if dirty:
            self.patches += 1
            levels = self._levels
            cur = set()
            for k in dirty:
                # a key created-then-deleted inside one window sits in the
                # dirty set but is no longer a leaf; with the key set
                # otherwise unchanged it contributes nothing to the tree
                i = self._pos.get(k)
                if i is None:
                    continue
                levels[0][i] = leaf_hash(leaf_item(k))
                cur.add(i)
            for lvl in range(len(levels) - 1):
                level, nxt = levels[lvl], levels[lvl + 1]
                parents = {i // 2 for i in cur}
                for p in parents:
                    li = 2 * p
                    if li + 1 < len(level):
                        nxt[p] = inner_hash(level[li], level[li + 1])
                    else:
                        nxt[p] = level[li]  # promoted odd node
                cur = parents
        return self._levels[-1][0]


@dataclass
class Proof:
    """Inclusion proof (reference crypto/merkle/proof.go:35)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root(self) -> Optional[bytes]:
        return _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if len(self.aunts) > MAX_AUNTS or self.total > (1 << MAX_AUNTS):
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        return self.compute_root() == root

    def encode(self) -> bytes:
        """(proto tendermint.crypto.Proof: total=1 index=2 leaf_hash=3
        aunts=4)"""
        from ..libs import protowire as pw

        w = pw.Writer()
        w.varint(1, self.total)
        if self.index:
            w.varint(2, self.index)
        w.bytes(3, self.leaf_hash)
        for a in self.aunts:
            w.bytes(4, a)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "Proof":
        from ..libs import protowire as pw

        f = pw.fields_dict(data)

        def as_int(v) -> int:
            if not isinstance(v, int):
                raise ValueError("expected varint field in Proof")
            return pw.varint_to_int64(v)

        return Proof(
            total=as_int(f.get(1, [0])[0] or 0),
            index=as_int(f.get(2, [0])[0] or 0),
            leaf_hash=pw.as_bytes(f.get(3, [b""])[0] or b""),
            aunts=[pw.as_bytes(a) for a in f.get(4, [])])


def _root_from_aunts(index: int, total: int, lh: bytes, aunts: List[bytes]) -> Optional[bytes]:
    if total == 0:
        return None
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> List[Proof]:
    """Root + one proof per item (reference crypto/merkle/proof.go:91)."""
    trails, _ = _trails_from_byte_slices(list(items))
    total = len(items)
    proofs = []
    for i, trail in enumerate(trails):
        node, aunts = trail, []
        cur = trail
        while cur.parent is not None:
            sib = cur.sibling
            if sib is not None:
                aunts.append(sib.hash)
            cur = cur.parent
        proofs.append(Proof(total=total, index=i, leaf_hash=node.hash, aunts=aunts))
    return proofs


class _Node:
    __slots__ = ("hash", "parent", "sibling")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent: Optional[_Node] = None
        self.sibling: Optional[_Node] = None


def _trails_from_byte_slices(items: List[bytes]):
    """Leaf trail nodes + root, built bottom-up (same promoted-odd-node
    scheme as hash_from_byte_slices; a promoted node's parent/sibling stay
    unset until it is paired, which matches the recursive reference shape,
    so the aunt lists — and therefore the proofs — are byte-identical)."""
    if len(items) == 0:
        return [], _Node(_sha256(b""))
    leaves = [_Node(leaf_hash(item)) for item in items]
    level = leaves
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            left, right = level[i], level[i + 1]
            parent = _Node(inner_hash(left.hash, right.hash))
            left.parent = parent
            left.sibling = right
            right.parent = parent
            right.sibling = left
            nxt.append(parent)
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return leaves, level[0]


# --- ProofOp chains (reference crypto/merkle/proof_op.go) -------------------
#
# Chained merkle proofs across trees (app store proofs through the light
# proxy): each operator maps leaf value(s) to its tree's root; the last root
# must equal the trusted one; keys are consumed right-to-left against the
# URL-encoded keypath (proof_key_path.go).

from urllib.parse import quote as _quote, unquote_to_bytes as _unquote


def _encode_byte_slice(b: bytes) -> bytes:
    """(libs/protoio encodeByteSlice) uvarint length prefix + bytes — the
    leaf encoding proof_value.go uses for both key and value hash."""
    from ..libs import protowire as pw

    return pw.encode_varint(len(b)) + b


@dataclass
class ProofOp:
    """(proto tendermint.crypto.ProofOp) the generic encoded operator."""

    type: str = ""
    key: bytes = b""
    data: bytes = b""


class ProofOperator:
    """(proof_op.go ProofOperator)"""

    def run(self, args: List[bytes]) -> List[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError

    def proof_op(self) -> ProofOp:
        raise NotImplementedError


class ValueOp(ProofOperator):
    """(proof_value.go) leaf = leafHash(encodeByteSlice(key) ||
    encodeByteSlice(sha256(value))) proven into a simple tree — the exact
    reference leaf encoding, so proofs interoperate with reference apps."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: Proof):
        self.key = key
        self.proof = proof

    def run(self, args: List[bytes]) -> List[bytes]:
        if len(args) != 1:
            raise ValueError(f"expected 1 arg, got {len(args)}")
        vhash = _sha256(args[0])
        leaf = leaf_hash(_encode_byte_slice(self.key)
                         + _encode_byte_slice(vhash))
        if leaf != self.proof.leaf_hash:
            raise ValueError("leaf mismatch in ValueOp")
        root = self.proof.compute_root()
        if root is None:
            raise ValueError("bad proof in ValueOp")
        return [root]

    def get_key(self) -> bytes:
        return self.key

    def proof_op(self) -> ProofOp:
        return ProofOp(self.TYPE, self.key, self.proof.encode())


def key_path(*keys: bytes) -> str:
    """(proof_key_path.go KeyPath) '/' + url-encoded key components."""
    return "".join("/" + _quote(k, safe="") for k in keys)


def keypath_to_keys(path: str) -> List[bytes]:
    if not path.startswith("/"):
        raise ValueError(f"keypath must start with '/': {path!r}")
    return [_unquote(p) for p in path[1:].split("/") if p]


class ProofRuntime:
    """(proof_op.go ProofRuntime) decoder registry + chained verification."""

    def __init__(self):
        self._decoders = {}  # type name -> ProofOp decoder

    def register_op_decoder(self, type_: str, dec) -> None:
        if type_ in self._decoders:
            raise ValueError(f"already registered for type {type_}")
        self._decoders[type_] = dec

    def decode(self, op: ProofOp) -> ProofOperator:
        dec = self._decoders.get(op.type)
        if dec is None:
            raise ValueError(f"unrecognized proof op type {op.type!r}")
        return dec(op)

    def verify_value(self, ops: List[ProofOp], root: bytes, keypath: str,
                     value: bytes) -> None:
        self.verify(ops, root, keypath, [value])

    def verify(self, ops: List[ProofOp], root: bytes, keypath: str,
               args: List[bytes]) -> None:
        """(proof_op.go ProofOperators.Verify) run the chain; keys consumed
        right-to-left; final root must match."""
        keys = keypath_to_keys(keypath)
        operators = [self.decode(op) for op in ops]
        for i, op in enumerate(operators):
            key = op.get_key()
            if key:
                if not keys:
                    raise ValueError(
                        f"key path has insufficient parts: got {key!r}")
                if keys[-1] != key:
                    raise ValueError(
                        f"key mismatch on operation #{i}: expected "
                        f"{keys[-1]!r} but got {key!r}")
                keys = keys[:-1]
            args = op.run(args)
        if root != args[0]:
            raise ValueError(
                f"calculated root hash is invalid: expected {root.hex()} "
                f"but got {args[0].hex()}")
        if keys:
            raise ValueError("keypath not consumed all")


def default_proof_runtime() -> ProofRuntime:
    """(proof_op.go DefaultProofRuntime) with the simple-value decoder."""
    prt = ProofRuntime()
    prt.register_op_decoder(
        ValueOp.TYPE,
        lambda op: ValueOp(op.key, Proof.decode(op.data)))
    return prt
