"""RFC 6962 merkle trees + proofs (reference crypto/merkle/{tree,proof}.go).

Domain-separated hashing: leaf = SHA256(0x00 || item), inner = SHA256(0x01 || l || r).
Empty tree hashes to SHA256(""). Split point is the largest power of two < n
(reference crypto/merkle/tree.go:85 getSplitPoint).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"

# Bound on proof depth, as in the reference (crypto/merkle/proof.go:14
# MaxAunts=100): rejects adversarial proofs instead of recursing unboundedly.
MAX_AUNTS = 100


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(item: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + item)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root (reference crypto/merkle/tree.go:9)."""
    n = len(items)
    if n == 0:
        return _sha256(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Inclusion proof (reference crypto/merkle/proof.go:35)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root(self) -> Optional[bytes]:
        return _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if len(self.aunts) > MAX_AUNTS or self.total > (1 << MAX_AUNTS):
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        return self.compute_root() == root


def _root_from_aunts(index: int, total: int, lh: bytes, aunts: List[bytes]) -> Optional[bytes]:
    if total == 0:
        return None
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> List[Proof]:
    """Root + one proof per item (reference crypto/merkle/proof.go:91)."""
    trails, _ = _trails_from_byte_slices(list(items))
    total = len(items)
    proofs = []
    for i, trail in enumerate(trails):
        node, aunts = trail, []
        cur = trail
        while cur.parent is not None:
            sib = cur.sibling
            if sib is not None:
                aunts.append(sib.hash)
            cur = cur.parent
        proofs.append(Proof(total=total, index=i, leaf_hash=node.hash, aunts=aunts))
    return proofs


class _Node:
    __slots__ = ("hash", "parent", "sibling")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent: Optional[_Node] = None
        self.sibling: Optional[_Node] = None


def _trails_from_byte_slices(items: List[bytes]):
    if len(items) == 0:
        return [], _Node(_sha256(b""))
    if len(items) == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = _split_point(len(items))
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.sibling = right_root
    right_root.parent = root
    right_root.sibling = left_root
    return lefts + rights, root
