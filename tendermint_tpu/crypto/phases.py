"""Per-segment dispatch-phase telemetry for the device verification plane.

The flagship 23x win (PROFILE_r05.json) was found by timing three phases of
every device dispatch by hand — host packing, the async kernel dispatch,
and the fetch wait for verdicts — across eight throwaway scripts. This
module makes those stamps a permanent, always-on part of the dispatch path
so the cost model is *measured by the system itself*:

* :class:`Segment` — one dispatched segment's monotonic phase stamps.
  ``begin()`` opens the pack phase, ``pack_done()`` closes it (stamped from
  inside the dispatcher via the thread-local active segment),
  ``dispatched()`` marks the async kernel call returning, and ``fetched()``
  closes the record when the verdict array is on the host. By construction
  ``pack_s + dispatch_s + fetch_s == t_end - t0`` for every record.
* a bounded ring of the last :data:`RING_CAPACITY` records plus cumulative
  :func:`phase_totals` — the inputs ``tools/device_profile.py`` and the
  debugdump ``device.json`` snapshot read;
* a ``DeviceMetrics`` hook (:func:`set_device_metrics`, wired by the node
  like ``crypto.batch.set_crypto_metrics``): phase histograms
  ``crypto_segment_phase_seconds{phase,plane}``, the per-segment size
  histogram, per-device dispatch counter / in-flight gauge, and the
  pipeline-overlap gauge;
* height-tagged ``seg_pack`` / ``seg_dispatch`` / ``seg_fetch`` tracer
  spans (emitted retroactively via ``tracer.complete`` when a segment
  closes) so ``trace_summary --by-height`` and ``trace_merge`` render
  device-pipeline occupancy next to the consensus stage timeline;
* :func:`phase_breakdown` — interval-union decomposition of a wall-clock
  window into exposed pack / exposed dispatch / device-in-flight shares
  (the shares sum to the accounted fraction of wall time — bench.py's
  flagship asserts they cover >=90%).

Deliberately jax-free: the host-fallback planes (crypto/batch.py scalar
route, the vote micro-batcher) count their batches here via
:func:`count_host` without dragging a broken jax install into the hot path.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..libs.trace import tracer

#: the phase catalog (README "Device profiling"): pack = host-side wire
#: packing, dispatch = the async kernel call returning, fetch = dispatch
#: return -> verdict bytes on host (in-flight transfer+compute+wait)
PHASE_NAMES = ("pack", "dispatch", "fetch")

#: last-N segment records kept for debugdump / the profiler
RING_CAPACITY = 256

#: synthetic tracer tid base for per-segment span tracks; each Segment
#: draws a distinct track (mod 256) so two calls in flight at once (a
#: live-plane flush under a sync-plane window) never share one — sharing
#: would render wall-time-overlapping slices as mis-nested in Perfetto
_SEG_TRACK_BASE = 0x5E60000
_TRACK_SEQ = itertools.count()

#: DeviceMetrics hook (libs/metrics.py), wired by node.py; None outside a
#: node process so library callers pay one None-check per segment
metrics = None


def set_device_metrics(m) -> None:
    global metrics
    metrics = m


# -- plane/height tagging context --------------------------------------------

# (plane, height): "sync" for block-sync/commit segments (default), "live"
# for the vote micro-batcher's flush dispatches. Height is tagged by the
# block-sync reactor around its window verify.
_ctx: "contextvars.ContextVar[Tuple[str, Optional[int]]]" = \
    contextvars.ContextVar("tmtpu_phase_ctx", default=("sync", None))


@contextlib.contextmanager
def telemetry(plane: Optional[str] = None, height: Optional[int] = None):
    """Tag segments recorded in this context with a plane and/or height.
    Thread-scoped like any contextvar: set it on the thread that calls the
    verifier (executor thunks must set it inside the thunk)."""
    cur_plane, cur_height = _ctx.get()
    token = _ctx.set((plane if plane is not None else cur_plane,
                      height if height is not None else cur_height))
    try:
        yield
    finally:
        _ctx.reset(token)


def context() -> Tuple[str, Optional[int]]:
    return _ctx.get()


# -- recording ----------------------------------------------------------------

_lock = threading.Lock()
_records: "collections.deque" = collections.deque(maxlen=RING_CAPACITY)
_ZERO_TOTALS = {
    "segments": 0, "sigs": 0,
    "pack_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0, "wait_s": 0.0,
    # per segmented call: union of in-flight intervals vs their sum
    "pipelined_calls": 0, "inflight_union_s": 0.0, "inflight_sum_s": 0.0,
    # scalar-routed batches: zero device phases, still counted
    "host_batches": 0, "host_sigs": 0,
}
_totals: Dict[str, float] = dict(_ZERO_TOTALS)

# thread-local active segment: the dispatcher stamps pack_done() from deep
# inside _dispatch_stream without threading a record through its signature
_active = threading.local()


def set_active(rec: "Segment"):
    prev = getattr(_active, "rec", None)
    _active.rec = rec
    return prev


def clear_active(prev) -> None:
    _active.rec = prev


def mark_pack_done() -> None:
    rec = getattr(_active, "rec", None)
    if rec is not None:
        rec.pack_done()


class Segment:
    """One device dispatch's phase stamps. Construct on the coordinating
    thread (captures plane/height from :func:`context` unless passed), then
    ``begin()`` on whichever thread packs, ``fetched()`` when the verdicts
    are host-resident."""

    __slots__ = ("plane", "height", "seg", "n_segs", "sigs", "chunk",
                 "device", "devices", "t0", "t_pack", "t_dispatch", "t_end",
                 "wait_s", "track")

    def __init__(self, sigs: int, chunk: int, seg: int = 0, n_segs: int = 1,
                 device: str = "device", plane: Optional[str] = None,
                 height: Optional[int] = None,
                 devices: Optional[Sequence[str]] = None):
        if plane is None or height is None:
            c_plane, c_height = _ctx.get()
            plane = plane if plane is not None else c_plane
            height = height if height is not None else c_height
        self.plane = plane
        self.height = height
        self.seg = seg
        self.n_segs = n_segs
        self.sigs = sigs
        self.chunk = chunk
        self.device = device
        self.devices = tuple(devices) if devices else (device,)
        self.t0 = None
        self.t_pack = None
        self.t_dispatch = None
        self.t_end = None
        self.wait_s = 0.0
        self.track = _SEG_TRACK_BASE + (next(_TRACK_SEQ) & 0xFF)

    def begin(self) -> "Segment":
        if self.t0 is None:
            self.t0 = time.perf_counter()
        return self

    def pack_done(self) -> "Segment":
        if self.t_pack is None:
            self.t_pack = time.perf_counter()
        return self

    def dispatched(self) -> "Segment":
        # state transition under the module lock: an abandon() racing from
        # the consuming thread (a sibling's fetch raised while this worker
        # was still packing) must never interleave with the gauge
        # increment — a late dispatch on a closed record would increment
        # in-flight with nobody left to drain it
        with _lock:
            if self.t_dispatch is not None or self.t_end is not None:
                return self
            self.t_dispatch = time.perf_counter()
            if self.t_pack is None:
                # no inner pack stamp (stubbed dispatch): attribute it all
                # to pack so the phases still tile the segment span exactly
                self.t_pack = self.t_dispatch
        m = metrics
        if m is not None:
            try:
                for d in self.devices:
                    m.device_dispatch_total.labels(d).inc()
                    m.device_inflight.labels(d).inc()
            except Exception:
                pass
        return self

    def abandon(self) -> "Segment":
        """Close a never-fetched segment (a relay fetch or a sibling
        segment raised): drains the in-flight gauge if it dispatched, and
        marks the record closed either way — so a pipeline worker still
        mid-pack when its call aborts cannot increment the gauge later
        with nobody left to drain it. No phase observation — the segment
        has no honest fetch time. No-op for already-fetched records."""
        with _lock:
            if self.t_end is not None:
                return self
            self.t_end = time.perf_counter()
            was_dispatched = self.t_dispatch is not None
        if not was_dispatched:
            return self  # closed pre-dispatch: gauge was never touched
        m = metrics
        if m is not None:
            try:
                for d in self.devices:
                    m.device_inflight.labels(d).inc(-1)
            except Exception:
                pass
        return self

    def fetched(self, wait_s: float = 0.0) -> "Segment":
        """Close the record: verdicts are on the host. ``wait_s`` is the
        portion of the fetch phase the *consuming* thread spent blocked
        (future wait + device-to-host copy) — the critical-path cost."""
        self.dispatched()  # defensive: a record may close without stamps
        t_end = time.perf_counter()
        with _lock:
            if self.t_end is not None:
                return self
            self.t_end = t_end
        self.wait_s = float(wait_s)
        pack_s = self.t_pack - self.t0
        dispatch_s = self.t_dispatch - self.t_pack
        fetch_s = t_end - self.t_dispatch
        rec = {
            "plane": self.plane, "height": self.height,
            "seg": self.seg, "n_segs": self.n_segs,
            "sigs": self.sigs, "chunk": self.chunk, "device": self.device,
            "t0": self.t0, "t_end": t_end,
            "pack_s": pack_s, "dispatch_s": dispatch_s, "fetch_s": fetch_s,
            "wait_s": self.wait_s,
        }
        if len(self.devices) > 1:
            rec["devices"] = list(self.devices)
        with _lock:
            _records.append(rec)
            _totals["segments"] += 1
            _totals["sigs"] += self.sigs
            _totals["pack_s"] += pack_s
            _totals["dispatch_s"] += dispatch_s
            _totals["fetch_s"] += fetch_s
            _totals["wait_s"] += self.wait_s
        m = metrics
        if m is not None:
            try:
                m.segment_phase_seconds.labels("pack", self.plane).observe(pack_s)
                m.segment_phase_seconds.labels("dispatch",
                                               self.plane).observe(dispatch_s)
                m.segment_phase_seconds.labels("fetch", self.plane).observe(fetch_s)
                m.segment_sigs.labels(self.plane).observe(self.sigs)
                for d in self.devices:
                    m.device_inflight.labels(d).inc(-1)
            except Exception:
                pass
        if tracer.enabled:
            args = {"plane": self.plane, "seg": self.seg,
                    "n_segs": self.n_segs, "sigs": self.sigs,
                    "device": self.device}
            if self.height is not None:
                args["height"] = self.height
            # synthetic per-segment track: pipelined (and cross-plane
            # concurrent) segments overlap in wall time, and all three
            # spans are emitted from the fetching thread — sharing a real
            # tid would render overlapping slices on one track as
            # mis-nested garbage in Perfetto. One track per segment shows
            # the occupancy honestly.
            tid = self.track
            tracer.complete("seg_pack", self.t0 * 1e6, pack_s * 1e6,
                            tid=tid, **args)
            tracer.complete("seg_dispatch", self.t_pack * 1e6,
                            dispatch_s * 1e6, tid=tid, **args)
            tracer.complete("seg_fetch", self.t_dispatch * 1e6,
                            fetch_s * 1e6, tid=tid, **args)
        return self


def count_host(plane: str, sigs: int) -> None:
    """A batch that never touched the device (scalar route / host
    fallback): zero device phases, but it must still COUNT — otherwise
    host-routed work silently vanishes from the device plane's accounting.
    Shows up as ``crypto_device_dispatch_total{device="host"}`` plus
    per-plane ``host_batches_<plane>`` / ``host_sigs_<plane>`` totals (the
    profiler / device.json answer to "which plane fell back how often")."""
    with _lock:
        _totals["host_batches"] += 1
        _totals["host_sigs"] += sigs
        for key, amt in ((f"host_batches_{plane}", 1),
                         (f"host_sigs_{plane}", sigs)):
            _totals[key] = _totals.get(key, 0) + amt
    m = metrics
    if m is not None:
        try:
            m.device_dispatch_total.labels("host").inc()
        except Exception:
            pass


def observe_overlap(recs: Sequence["Segment"]) -> Optional[float]:
    """Pipeline-overlap ratio for one segmented call: wall time with >=1
    segment in flight (union of [dispatched, fetched] intervals) over the
    SUM of in-flight durations. 1.0 = fully serial dispatches; 0.5 = a
    2-deep pipeline whose in-flight windows fully overlap."""
    iv = [(r.t_dispatch, r.t_end) for r in recs
          if r.t_dispatch is not None and r.t_end is not None]
    if not iv:
        return None
    total = sum(b - a for a, b in iv)
    if total <= 0:
        return None
    ratio = _union_len(iv) / total
    with _lock:
        _totals["pipelined_calls"] += 1
        _totals["inflight_union_s"] += _union_len(iv)
        _totals["inflight_sum_s"] += total
    m = metrics
    if m is not None:
        try:
            m.pipeline_overlap_ratio.set(ratio)
        except Exception:
            pass
    return ratio


# -- read side ----------------------------------------------------------------

def recent_segments(n: Optional[int] = None) -> List[dict]:
    """Copies of the last ``n`` (default: all retained) segment records."""
    with _lock:
        out = [dict(r) for r in _records]
    return out if n is None else out[-n:]


def phase_totals() -> Dict[str, float]:
    with _lock:
        return dict(_totals)


def reset() -> None:
    with _lock:
        _records.clear()
        _totals.clear()
        _totals.update(_ZERO_TOTALS)


# -- wall-clock decomposition -------------------------------------------------

def _union_len(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [a, b) intervals."""
    total = 0.0
    end = None
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def phase_breakdown(records: Sequence[dict], wall_t0: float,
                    wall_t1: float) -> Dict[str, float]:
    """Decompose a wall-clock window into device-plane phase shares from
    the segment records inside it.

    Interval-union accounting keeps the shares physical under pipelining:
    ``device_share`` is the union of in-flight intervals; ``pack`` /
    ``dispatch`` exposed shares count only host time NOT hidden behind an
    in-flight segment. The three exposed shares sum to ``accounted_share``
    (<= 1), while ``*_s`` totals sum raw per-thread seconds (which CAN
    exceed wall — that is the overlap working)."""
    wall = max(wall_t1 - wall_t0, 1e-9)
    pack_iv, disp_iv, fly_iv = [], [], []
    pack_s = dispatch_s = fetch_s = wait_s = 0.0
    sigs = 0
    for r in records:
        t0 = r["t0"]
        t_pack = t0 + r["pack_s"]
        t_disp = t_pack + r["dispatch_s"]
        pack_iv.append((t0, t_pack))
        disp_iv.append((t_pack, t_disp))
        fly_iv.append((t_disp, r["t_end"]))
        pack_s += r["pack_s"]
        dispatch_s += r["dispatch_s"]
        fetch_s += r["fetch_s"]
        wait_s += r["wait_s"]
        sigs += r["sigs"]
    fly_u = _union_len(fly_iv)
    pack_exposed = _union_len(fly_iv + pack_iv) - fly_u
    disp_exposed = _union_len(fly_iv + pack_iv + disp_iv) \
        - _union_len(fly_iv + pack_iv)
    busy = fly_u + pack_exposed + disp_exposed
    fly_sum = sum(b - a for a, b in fly_iv)
    return {
        "wall_s": wall, "busy_s": busy,
        "accounted_share": busy / wall,
        "segments": len(records), "sigs": sigs,
        "pack_s": pack_s, "dispatch_s": dispatch_s,
        "fetch_s": fetch_s, "wait_s": wait_s,
        "pack_share_total": pack_s / wall,
        "pack_share_exposed": pack_exposed / wall,
        "dispatch_share_exposed": disp_exposed / wall,
        "device_share": fly_u / wall,
        "overlap_ratio": (fly_u / fly_sum) if fly_sum > 0 else 1.0,
    }
