"""BatchVerifier — the batched verification seam the reference lacks.

The reference verifies one signature at a time (crypto/crypto.go:22-28 has
only PubKey.VerifySignature; SURVEY.md north star). Here, callers collect
(pubkey, msg, sig) tuples and verify them in one device call:

    bv = BatchVerifier()
    bv.add(pub, msg, sig)          # any number of times
    ok_all, per_item = bv.verify() # one TPU kernel launch

Backends:
* "jax"  — the batched TPU/CPU-XLA kernel (ed25519_jax.batch_verify);
* "host" — scalar loop over PubKey.verify_signature (OpenSSL or pure-Python).

Decisions are byte-identical across backends (enforced by differential
tests). Default backend: "jax" when a device batch is worthwhile, "host" for
tiny batches where kernel-launch latency would dominate — the threshold is
overridable for benchmarking. Set env TMTPU_BATCH_BACKEND to pin one.
"""

from __future__ import annotations

import contextvars
import logging
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import Ed25519PubKey, PubKey
from . import phases as _phases
from ..libs.faults import faults
from ..libs.trace import tracer
from .breaker import classify_device_error, device_breaker

logger = logging.getLogger("tmtpu.batch")

# below this many signatures the host scalar loop beats a device round-trip.
# The break-even point depends on per-dispatch overhead: ~100 us on a local
# chip, ~100 ms through a remote relay — so "auto" calibrates once.
DEFAULT_DEVICE_THRESHOLD = 16
_HOST_SIGS_PER_SEC_ESTIMATE = 7000.0  # OpenSSL verify ~140 us/op
_calibrated_threshold: Optional[int] = None


# routed batches must never lose to the scalar loop: bias the calibrated
# break-even up so near-threshold commits stay on host (the device win at
# the margin is ~0, the loss through a slow relay is 5-10x)
_CALIBRATION_SAFETY = 1.25


def device_threshold() -> int:
    """Break-even batch size for the device path, measured once: dispatch
    overhead (seconds) x host verify rate. Override: TMTPU_DEVICE_THRESHOLD.

    The probe carries a fresh ~32KB payload (a ~150-sig commit's wire
    weight): a payload-free jit call measures only the fixed dispatch cost
    and badly underestimates relay-attached devices, which is how
    sub-threshold commits ended up routed to a path 5x slower than the
    scalar loop (BENCH_r05 verify_commit_150_device_routed at 0.18x).
    Fresh random bytes per call defeat relay result-caching."""
    global _calibrated_threshold
    env = os.environ.get("TMTPU_DEVICE_THRESHOLD")
    if env:
        return int(env)
    if _calibrated_threshold is None:
        try:
            import time

            import jax
            import jax.numpy as jnp
            import numpy as np

            f = jax.jit(lambda x: x.astype(jnp.int32).sum())

            def _probe() -> float:
                x = np.frombuffer(os.urandom(256 * 128),
                                  dtype=np.uint8).reshape(256, 128)
                t0 = time.perf_counter()
                np.asarray(f(x))
                return time.perf_counter() - t0

            _probe()  # compile
            overhead = min(_probe(), _probe())
            _calibrated_threshold = max(
                DEFAULT_DEVICE_THRESHOLD,
                int(overhead * _HOST_SIGS_PER_SEC_ESTIMATE
                    * _CALIBRATION_SAFETY))
        except Exception as e:
            # calibration failure is routing advice, not correctness: fall
            # back to the static default — but say so, a silent except here
            # once hid a broken relay for a whole bench run
            logger.warning("device-threshold calibration failed (%s); "
                           "using default %d", e, DEFAULT_DEVICE_THRESHOLD)
            _calibrated_threshold = DEFAULT_DEVICE_THRESHOLD
    return _calibrated_threshold


# verdicts precomputed by a wider batching scope (e.g. the light client's
# chain-batched verifier): (pk_bytes, msg, sig) -> bool. Consulted before any
# dispatch so an enclosing batch costs ONE device call total.
precomputed_verdicts: "contextvars.ContextVar[Optional[Dict]]" = \
    contextvars.ContextVar("tmtpu_precomputed_verdicts", default=None)


def precompute(items: Sequence[Tuple[PubKey, bytes, bytes]],
               plane: str = "light", backend: Optional[str] = None,
               device_threshold: Optional[int] = None
               ) -> Dict[Tuple[bytes, bytes, bytes], bool]:
    """Verify ``(pub, msg, sig)`` tuples in ONE batched call and return the
    verdict map shaped for :data:`precomputed_verdicts` — the entry point a
    wider batching scope (the light-serving coalescer, the chain-batched
    verifier) uses to fold many independent verifications into a single
    device dispatch, then replay exact scalar semantics against the map."""
    bv = BatchVerifier(backend=backend, device_threshold=device_threshold,
                       plane=plane)
    for pub, msg, sig in items:
        bv.add(pub, msg, sig)
    _, verdicts = bv.verify()
    return {(items[i][0].bytes(), items[i][1], items[i][2]):
            bool(verdicts[i]) for i in range(len(items))}

# routing observability (VERDICT r3: batch sizes / routing decisions were
# invisible): cumulative counters, cheap ints only
stats = {
    "host_batches": 0, "host_sigs": 0,
    "device_batches": 0, "device_sigs": 0,
    "precomputed_batches": 0, "precomputed_sigs": 0,
    "largest_batch": 0,
    # robustness plane: device attempts that raised (fell back to host) and
    # batches the open circuit breaker kept off the device entirely
    "device_errors": 0, "breaker_rejections": 0,
}

# CryptoMetrics hook, wired by the node (same idiom as p2p's
# set_p2p_metrics): None outside a node process, so library callers
# (tests, bench, light client as a library) pay one None-check per batch
metrics = None


def set_crypto_metrics(m) -> None:
    global metrics
    metrics = m


def _padded_slots(n: int, chunk: int = 2048) -> int:
    """Device slots a batch of n occupies after padding: the stream path
    rounds up to whole chunks, the one-call path to the next power-of-two
    lane bucket (ed25519_jax.verify._pad_to). Used for the pad-waste gauge
    only — approximate is fine, wrong can't corrupt anything."""
    if n <= 0:
        return 0
    if n > chunk:
        return -(-n // chunk) * chunk
    size = 128  # LANE
    while size < n:
        size *= 2
    return size


class BatchVerifier:
    def __init__(self, backend: Optional[str] = None,
                 device_threshold: Optional[int] = None,
                 plane: str = "votes"):
        self._backend = backend or os.environ.get("TMTPU_BATCH_BACKEND") or "auto"
        if self._backend not in ("auto", "jax", "host"):
            raise ValueError(f"unknown batch backend {self._backend!r}")
        self._threshold = device_threshold
        # metric label only: which verification plane this batch serves
        # ("votes" live commits, "light" light/fast-sync, "evidence")
        self.plane = plane
        self._pks: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []
        self._non_ed25519: List[Tuple[int, PubKey]] = []
        self._columns = None

    def __len__(self) -> int:
        return len(self._pks)

    def set_columns(self, columns) -> None:
        """Columnar sign-bytes (crypto/signcols.SignColumns) aligned 1:1
        with the rows added so far — a packing HINT for the device path
        (skips per-segment structure re-discovery). Rows must reconstruct
        byte-identically to the added msgs; verdicts cannot change either
        way. Cleared by verify() with the rest of the batch."""
        self._columns = columns

    def add(self, pub: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub, Ed25519PubKey):
            # rare key types verify on host; remember position for the verdict
            self._non_ed25519.append((len(self._pks), pub))
        self._pks.append(pub.bytes())
        self._msgs.append(msg)
        self._sigs.append(sig)

    def verify(self) -> Tuple[bool, np.ndarray]:
        """-> (all_valid, per-item bool array). Resets the collected batch."""
        pks, msgs, sigs = self._pks, self._msgs, self._sigs
        non_ed = self._non_ed25519
        columns = self._columns
        self._pks, self._msgs, self._sigs, self._non_ed25519 = [], [], [], []
        self._columns = None
        n = len(pks)
        if n == 0:
            return True, np.zeros(0, dtype=bool)

        stats["largest_batch"] = max(stats["largest_batch"], n)
        pre = precomputed_verdicts.get()
        if pre is not None:
            hits = [pre.get((pks[i], msgs[i], sigs[i])) for i in range(n)]
            if all(h is not None for h in hits):
                out = np.array(hits, dtype=bool)
                stats["precomputed_batches"] += 1
                stats["precomputed_sigs"] += n
                if metrics is not None:
                    metrics.precomputed_hits_total.labels(self.plane).inc()
                return bool(out.all()), out

        backend = self._backend
        if backend == "auto":
            thr = (self._threshold if self._threshold is not None
                   else device_threshold())
            backend = "jax" if n >= thr else "host"
        if backend == "jax" and not device_breaker.allow():
            # breaker OPEN: zero device attempts until the cooldown admits a
            # half-open probe; the host path keeps verifying meanwhile
            backend = "host"
            stats["breaker_rejections"] += 1
            if metrics is not None:
                metrics.device_fallbacks_total.labels("breaker_open").inc()

        non_ed_idx = {i: pk for i, pk in non_ed}

        def _host_verify() -> np.ndarray:
            res = np.zeros(n, dtype=bool)
            for i in range(n):
                pub = non_ed_idx.get(i) or Ed25519PubKey(pks[i])
                res[i] = pub.verify_signature(msgs[i], sigs[i])
            return res

        route = "device" if backend == "jax" else "scalar"
        t0 = time.perf_counter()
        # tracer.span is a shared no-op when disabled (one attribute check
        # inside span() plus the kwargs dict — noise next to any verify)
        with tracer.span("batch_verify", n=n, route=route,
                         plane=self.plane) as sp:
            if backend == "jax":
                try:
                    # chaos seam: an armed `device.batch_verify` site raises
                    # here, exercising the same fallback a real device error
                    # takes
                    faults.inject("device.batch_verify")
                    from .ed25519_jax import batch_verify_stream

                    ed_pos = [i for i in range(n) if i not in non_ed_idx]
                    out = np.zeros(n, dtype=bool)
                    if ed_pos:
                        # batch_verify_stream == batch_verify below one
                        # chunk; above, it scans fixed-size chunks inside
                        # one device execution. The columnar hint only
                        # survives when it still aligns 1:1 with the rows
                        # the kernel sees (no non-ed25519 holes)
                        cols = (columns if columns is not None
                                and len(ed_pos) == n
                                and len(columns) == n else None)
                        ed_out = batch_verify_stream(
                            [pks[i] for i in ed_pos],
                            [msgs[i] for i in ed_pos],
                            [sigs[i] for i in ed_pos],
                            columns=cols)
                        out[ed_pos] = ed_out
                    # rare non-ed25519 keys verify on host, verdicts merged
                    # by index
                    for i, pub in non_ed_idx.items():
                        out[i] = pub.verify_signature(msgs[i], sigs[i])
                except Exception as e:
                    # a device failure never surfaces to the caller: the
                    # batch re-verifies on host (byte-identical verdicts)
                    # and the breaker remembers, so persistent failure stops
                    # paying the device attempt at all
                    reason = classify_device_error(e)
                    logger.warning(
                        "device batch verify failed (%s, n=%d, plane=%s): "
                        "%s — re-verifying on host", reason, n, self.plane, e)
                    device_breaker.record_failure()
                    stats["device_errors"] += 1
                    if metrics is not None:
                        metrics.device_fallbacks_total.labels(reason).inc()
                    route = "scalar"
                    # keep the trace honest: the span was opened with
                    # route="device" but the work below is the host path
                    sp.set(route="scalar", device_error=reason)
                    t0 = time.perf_counter()  # charge only the host verify
                    out = _host_verify()
                else:
                    if ed_pos:
                        # only real device evidence closes/holds the
                        # breaker: an all-non-ed25519 batch never touched
                        # the device, and letting it report success would
                        # falsely close a half-open probe
                        device_breaker.record_success()
            else:
                out = _host_verify()
        stats["device_batches" if route == "device" else "host_batches"] += 1
        stats["device_sigs" if route == "device" else "host_sigs"] += n
        if route != "device":
            # scalar-routed (or device-fallback) batches record zero device
            # phases but still count on the device plane's ledger
            _phases.count_host(self.plane, n)
        if metrics is not None:
            elapsed = time.perf_counter() - t0
            metrics.routing_decisions_total.labels(route, self.plane).inc()
            metrics.batch_size.labels(route, self.plane).observe(n)
            metrics.verify_latency_seconds.labels(route,
                                                  self.plane).observe(elapsed)
            if route == "device":
                n_ed = n - len(non_ed_idx)  # only ed25519 rows ride the kernel
                slots = _padded_slots(n_ed)
                if slots:
                    metrics.pad_waste_ratio.labels(self.plane).set(
                        (slots - n_ed) / slots)
        return bool(out.all()), out
