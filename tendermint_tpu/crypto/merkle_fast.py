"""Batched SHA-256 kernels for the merkle fast path (crypto/merkle.py).

App-hash merkle trees hash many short fixed-length messages per commit —
inner nodes are always 65 bytes (0x01 || left32 || right32), leaf items of
one kvstore level mostly share a length — so the whole tree level fits one
vectorized compression: pack n messages into an (n, padded_words) uint32
array and run the SHA-256 rounds as ~640 elementwise u32 ops over it.
SHA-256 is pure u32 arithmetic, so unlike the Ed25519 challenge hash
(ed25519_jax/sha512.py, u64 emulated as u32 pairs) no wide-word emulation
is needed; the same round function runs under numpy (host vectorized) or
``jax.numpy`` (device, jitted per padded-block count — the only static
shape). Differential tests pin both to hashlib; crypto/merkle.py routes
between hashlib / numpy / device and owns breaker + threshold policy.
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_IV = (0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
       0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19)


def _sha256_words(xp, words, n_blocks: int):
    """SHA-256 over n padded messages; ``words`` is (n, n_blocks*16) u32
    big-endian schedule input. Returns 8 arrays of shape (n,). Generic
    over numpy / jax.numpy — u32 adds wrap identically on both."""
    u = xp.uint32

    def rotr(x, k: int):
        return (x >> u(k)) | (x << u(32 - k))

    n = words.shape[0]
    hs = [xp.full((n,), u(iv)) for iv in _IV]
    for blk in range(n_blocks):
        w = [words[:, 16 * blk + t] for t in range(16)]
        for t in range(16, 64):
            x15, x2 = w[t - 15], w[t - 2]
            s0 = rotr(x15, 7) ^ rotr(x15, 18) ^ (x15 >> u(3))
            s1 = rotr(x2, 17) ^ rotr(x2, 19) ^ (x2 >> u(10))
            w.append(w[t - 16] + s0 + w[t - 7] + s1)
        a, b, c, d, e, f, g, h = hs
        for t in range(64):
            s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + u(int(_K[t])) + w[t]
            s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            t2 = s0 + maj
            h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
        hs = [hs[0] + a, hs[1] + b, hs[2] + c, hs[3] + d,
              hs[4] + e, hs[5] + f, hs[6] + g, hs[7] + h]
    return hs


def _pad_fixed(msgs: List[bytes], length: int) -> np.ndarray:
    """Pack n equal-length messages into their padded big-endian u32
    schedule words, shape (n, blocks*16)."""
    n = len(msgs)
    padded = ((length + 8) // 64 + 1) * 64
    buf = np.zeros((n, padded), dtype=np.uint8)
    if length:
        buf[:, :length] = np.frombuffer(
            b"".join(msgs), dtype=np.uint8).reshape(n, length)
    buf[:, length] = 0x80
    buf[:, padded - 8:] = np.frombuffer(
        struct.pack(">Q", length * 8), dtype=np.uint8)
    return buf.view(">u4").astype(np.uint32)


def _digests(hs_stacked: np.ndarray, n: int) -> List[bytes]:
    out = hs_stacked.astype(">u4").tobytes()
    return [out[i * 32:(i + 1) * 32] for i in range(n)]


def sha256_many_np(msgs: List[bytes]) -> List[bytes]:
    """Vectorized host path; all messages must share one length."""
    words = _pad_fixed(msgs, len(msgs[0]))
    hs = _sha256_words(np, words, words.shape[1] // 16)
    return _digests(np.stack(hs, axis=1), len(msgs))


# -- device path (jitted per padded-block count) ------------------------------

_jit_cache: dict = {}
_device_state: List[bool] = []  # lazily probed once


def device_ready() -> bool:
    if not _device_state:
        try:
            import jax

            _device_state.append(bool(jax.devices()))
        except Exception:
            _device_state.append(False)
    return _device_state[0]


def _device_fn(n_blocks: int):
    fn = _jit_cache.get(n_blocks)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def run(words):
            return jnp.stack(_sha256_words(jnp, words, n_blocks), axis=1)

        fn = jax.jit(run)
        _jit_cache[n_blocks] = fn
    return fn


def sha256_many_device(msgs: List[bytes]) -> List[bytes]:
    """Device path: same packing, jitted rounds, host fetch. Raises on any
    device trouble — the caller (crypto/merkle.py) owns breaker fallback."""
    words = _pad_fixed(msgs, len(msgs[0]))
    out = np.asarray(_device_fn(words.shape[1] // 16)(words))
    return _digests(out, len(msgs))
