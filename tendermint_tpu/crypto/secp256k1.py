"""secp256k1 key type (reference crypto/secp256k1/secp256k1.go — pure-Go
btcec there; OpenSSL-backed here).

Semantics mirror the reference:
* 33-byte compressed pubkeys;
* Bitcoin-style address: RIPEMD160(SHA256(compressed pubkey))
  (secp256k1.go:12 Address);
* signatures are 64-byte R||S with low-S normalization
  (secp256k1.go Sign via btcec: "Serialize" compact form without recovery
  id); verification rejects malleable high-S signatures the same way.

Host-only: consensus keys stay ed25519 (the batched device path); secp256k1
is the optional account/validator key type the reference also supports.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

from . import PrivKey, PubKey

# secp256k1 group order
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

KEY_TYPE = "secp256k1"
PUBKEY_SIZE = 33
SIG_SIZE = 64


def _ripemd160(b: bytes) -> bytes:
    h = hashlib.new("ripemd160")
    h.update(b)
    return h.digest()


class Secp256k1PubKey(PubKey):
    type_name = "tendermint/PubKeySecp256k1"

    def __init__(self, key: bytes):
        if len(key) != PUBKEY_SIZE:
            raise ValueError(f"secp256k1 pubkey must be {PUBKEY_SIZE} bytes")
        self.key = key
        self._pk = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), key)

    def address(self) -> bytes:
        """RIPEMD160(SHA256(pubkey)) — Bitcoin style (secp256k1.go:12)."""
        return _ripemd160(hashlib.sha256(self.key).digest())

    def bytes(self) -> bytes:
        return self.key

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIG_SIZE:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < _N and 0 < s < _N):
            return False
        if s > _N // 2:  # reject malleable high-S (btcec Verify convention)
            return False
        try:
            self._pk.verify(encode_dss_signature(r, s), msg,
                            ec.ECDSA(hashes.SHA256()))
            return True
        except InvalidSignature:
            return False

    def __eq__(self, other):
        return isinstance(other, Secp256k1PubKey) and other.key == self.key

    def __hash__(self):
        return hash(self.key)


class Secp256k1PrivKey(PrivKey):
    type_name = "tendermint/PrivKeySecp256k1"

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        self.key = key
        self._sk = ec.derive_private_key(int.from_bytes(key, "big"),
                                         ec.SECP256K1())

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Secp256k1PrivKey":
        if seed is not None:
            # deterministic from seed: hash to scalar (test convenience; the
            # reference's GenPrivKeySecp256k1 hashes the secret similarly)
            d = int.from_bytes(hashlib.sha256(seed).digest(), "big") % (_N - 1) + 1
            return Secp256k1PrivKey(d.to_bytes(32, "big"))
        sk = ec.generate_private_key(ec.SECP256K1())
        d = sk.private_numbers().private_value
        return Secp256k1PrivKey(d.to_bytes(32, "big"))

    def bytes(self) -> bytes:
        return self.key

    def sign(self, msg: bytes) -> bytes:
        der = self._sk.sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > _N // 2:  # low-S normalization (btcec Sign)
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1PubKey:
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        return Secp256k1PubKey(self._sk.public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint))
