"""Multi-device sharded streaming verifier — the dispatcher that finally
uses all N chips.

``MULTICHIP_r0*.json`` showed 8 devices present while every production
dispatch went to chip 0; the one multichip entry point
(:func:`sharded.batch_verify_sharded`) is a one-shot shard_map call nothing
routed through. This module shards :func:`verify.batch_verify_stream`
segments **round-robin across a device pool**, with:

* **one dedicated packing/transfer worker thread per device** — the
  PROFILE_r05 relay cost model's load-bearing facts: host->device transfer
  is serial *per thread*, a single thread's dispatches do not pipeline, but
  a second thread's pack+transfer overlaps an in-flight execution. N lanes
  x N devices therefore scale near-linearly until host packing saturates;
* **per-device circuit breakers** (crypto/breaker.lane_breaker): a sick
  chip degrades the pool to N-1 healthy lanes — its queued segments
  re-shard onto healthy peers with zero dropped signatures — instead of
  collapsing the whole verification plane to host fallback. Only when
  every lane is sick does the call raise, and then the caller's shared
  ``device_breaker`` fallback takes over exactly as before;
* **device-aware segment sizing** fed by the PR 8 cost model
  (``tools/device_profile.py cost-model`` output via
  ``TMTPU_DEVICE_PROFILE``): segments are sized so per-dispatch fixed cost
  stays a small fraction of per-segment transfer time. ``TMTPU_SEG_CHUNKS``
  still overrides everything;
* **per-lane chaos sites** ``device.lane.<platform>:<id>`` (libs/faults):
  arm exactly one device label and watch the pool degrade.

Verdicts are byte-identical to the single-device path: segments are exact
slices of the same packed wire format, fetched and reassembled in order
(differential tests in tests/test_multidevice_stream.py). Every segment
records pack/dispatch/fetch phases with its lane's device label, so the
PR 8 ``crypto_device_dispatch_total{device}`` / ``crypto_device_inflight``
series and the Perfetto segment tracks show per-chip occupancy for free.

Knobs: ``TMTPU_VERIFY_DEVICES`` (device count; 0/1 disables the pool,
unset = all visible devices), ``TMTPU_MULTIDEV_MIN_SIGS`` (engage
threshold, default 2x SEG_MIN_SIGS), ``TMTPU_DEVICE_BREAKER_THRESHOLD`` /
``TMTPU_DEVICE_BREAKER_COOLDOWN_S`` (per-lane breakers). On machines with
one physical chip, ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
exercises the full dispatch topology against a forced host mesh.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax

from ...libs.faults import faults
from .. import phases
from ..breaker import lane_breaker
from . import verify as V

logger = logging.getLogger("tmtpu.multidevice")

ENV_DEVICES = "TMTPU_VERIFY_DEVICES"
ENV_MIN_SIGS = "TMTPU_MULTIDEV_MIN_SIGS"
ENV_PROFILE = "TMTPU_DEVICE_PROFILE"

#: fault-site family: one site per lane, e.g. ``device.lane.tpu:3``
LANE_SITE_PREFIX = "device.lane."

#: keep per-dispatch fixed cost under ~1/OVERHEAD_TARGET of a segment's
#: transfer time when sizing segments from a cost model
OVERHEAD_TARGET = 9.0
#: ~wire bytes per signature on the dense path (R+A+s + padded preimage)
APPROX_BYTES_PER_SIG = 300.0


class AllLanesFailed(RuntimeError):
    """Every pool lane is sick or failed this batch; the caller's shared
    device_breaker / host-fallback path takes over."""


def _seg_chunks_from_cost_model(doc: dict, chunk: int = 2048) -> Optional[int]:
    """Segment size (in scan chunks) from a device_profile cost-model doc:
    big enough that the fixed dispatch cost is <= ~1/OVERHEAD_TARGET of the
    segment's per-thread transfer time. None when the doc lacks the
    numbers (e.g. bandwidth below the ladder's noise floor)."""
    try:
        res = doc["results"]
        fixed_s = float(res["fixed_dispatch_ms"]["min"]) / 1e3
        bw = res["transfer"]["bandwidth_mbps"]
        if bw is None or bw <= 0 or fixed_s <= 0:
            return None
        chunk_transfer_s = chunk * APPROX_BYTES_PER_SIG / (bw * (1 << 20))
        if chunk_transfer_s <= 0:
            return None
        need = OVERHEAD_TARGET * fixed_s / chunk_transfer_s
        return max(2, min(64, -(-int(need * 1000) // 1000)))
    except (KeyError, TypeError, ValueError):
        return None


def default_seg_chunks() -> int:
    """Per-lane segment size: TMTPU_SEG_CHUNKS wins; else a cost model
    named by TMTPU_DEVICE_PROFILE; else verify.SEG_CHUNKS."""
    if os.environ.get("TMTPU_SEG_CHUNKS"):
        return V.SEG_CHUNKS  # verify.py already parsed the env knob
    path = os.environ.get(ENV_PROFILE)
    if path:
        try:
            with open(path) as f:
                doc = json.load(f)
            if doc.get("kind") == "cost-model":
                derived = _seg_chunks_from_cost_model(doc)
                if derived is not None:
                    return derived
        except (OSError, ValueError) as e:
            logger.warning("%s=%r unusable (%s); using SEG_CHUNKS=%d",
                           ENV_PROFILE, path, e, V.SEG_CHUNKS)
    return V.SEG_CHUNKS


def plan_segments(k_total: int, n_lanes: int,
                  seg_chunks: int) -> List[Tuple[int, int]]:
    """Deterministic shard plan: ``[(size_chunks, lane_index), ...]``.

    Near-equal segments of at most ``seg_chunks`` scan-chunks, at least
    two per lane when the batch is big enough (each lane's worker then
    packs segment i+1 while its segment i executes — the same
    double-buffering the single-device path uses, now per lane), assigned
    round-robin so the plan is a pure function of (k_total, n_lanes,
    seg_chunks)."""
    if k_total <= 0:
        return []
    n_segs = min(k_total, max(-(-k_total // seg_chunks),
                              min(k_total, 2 * n_lanes)))
    base, extra = divmod(k_total, n_segs)
    sizes = [base + (1 if i < extra else 0) for i in range(n_segs)]
    return [(s, i % n_lanes) for i, s in enumerate(sizes)]


class DeviceLane:
    """One device plus its dedicated packing/transfer worker and breaker."""

    __slots__ = ("index", "device", "label", "breaker", "pool")

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.label = f"{device.platform}:{device.id}"
        self.breaker = lane_breaker(self.label)
        self.pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"ed25519-lane{index}")

    def shutdown(self) -> None:
        self.pool.shutdown(wait=False)


class MultiDeviceStream:
    """Shards one batch_verify_stream call across a pool of device lanes."""

    def __init__(self, devices: Optional[Sequence] = None,
                 min_sigs: Optional[int] = None,
                 seg_chunks: Optional[int] = None):
        if devices is None:
            devices = jax.devices()
        self.lanes = [DeviceLane(i, d) for i, d in enumerate(devices)]
        env_min = os.environ.get(ENV_MIN_SIGS)
        self.min_sigs = (min_sigs if min_sigs is not None
                         else int(env_min) if env_min
                         else 2 * V.SEG_MIN_SIGS)
        self.seg_chunks = (seg_chunks if seg_chunks is not None
                           else default_seg_chunks())
        self.stats = collections.Counter()

    # -- health -------------------------------------------------------------

    def eligible_lanes(self) -> List[DeviceLane]:
        """Lanes whose breakers would admit a dispatch (read-only check)."""
        return [l for l in self.lanes if l.breaker.peek()]

    def engaged(self, n: int) -> bool:
        """Should a batch of n shard across the pool? Needs enough
        signatures to amortize per-device dispatch overhead and at least
        two healthy lanes (with one, the single-device path is strictly
        better — no cross-lane coordination)."""
        return n >= self.min_sigs and len(self.eligible_lanes()) >= 2

    # -- the dispatcher -----------------------------------------------------

    def verify(self, pks, msgs, sigs, chunk: int, columns=None,
               t_entry: Optional[float] = None) -> np.ndarray:
        """(N,) bool — the batch as round-robin segments across healthy
        lanes, fetched and reassembled in order. A lane failure re-shards
        that segment onto the next healthy lane (zero dropped signatures)
        and feeds the lane's breaker; :class:`AllLanesFailed` surfaces only
        when no healthy lane remains."""
        n = len(pks)
        lanes = self.eligible_lanes()
        if not lanes:
            raise AllLanesFailed(
                f"0/{len(self.lanes)} device lanes healthy")
        plan = plan_segments(-(-n // chunk), len(lanes), self.seg_chunks)
        bounds, lo = [], 0
        for size, lane_i in plan:
            hi = min(lo + size * chunk, n)
            bounds.append((lo, hi, lane_i))
            lo = hi
        plane, height = phases.context()
        all_recs: List[phases.Segment] = []

        def submit(seg_i, a, b, lane):
            rec = phases.Segment(
                sigs=b - a, chunk=chunk, seg=seg_i, n_segs=len(bounds),
                device=lane.label, plane=plane, height=height)
            all_recs.append(rec)
            col = columns.slice(a, b) if columns is not None else None
            fut = lane.pool.submit(
                self._run_lane, lane, rec, pks[a:b], msgs[a:b], sigs[a:b],
                chunk, col)
            return rec, fut

        # admit only lanes the plan actually dispatches to (allow() is the
        # MUTATING breaker check: it latches a half-open probe slot, and a
        # probe on a lane that never gets a segment would stay phantom-
        # in-flight for a whole cooldown, starving the lane's rejoin)
        admitted = []
        for lane in lanes[:min(len(bounds), len(lanes))]:
            if lane.breaker.allow():
                admitted.append(lane)
        if not admitted:
            raise AllLanesFailed(
                f"0/{len(self.lanes)} device lanes admitted a dispatch")
        lane_of = lambda i: admitted[i % len(admitted)]

        # windowed submission: at most ~2 queued segments per lane (the
        # same depth the single-device pipeline keeps). Submitting the
        # whole plan up front would hold every segment's packed host
        # arrays + dispatched device buffers live at once — unbounded by
        # batch size instead of by lane count.
        window = 2 * len(admitted)
        recs: List[Optional[phases.Segment]] = [None] * len(bounds)
        futs: List = [None] * len(bounds)
        for seg_i in range(min(window, len(bounds))):
            a, b, lane_i = bounds[seg_i]
            recs[seg_i], futs[seg_i] = submit(seg_i, a, b, lane_of(lane_i))
        if t_entry is not None:
            # stream-entry host work (bucket grouping) is critical-path
            # pack cost; charge it to segment 0 like the single-device path
            recs[0].t0 = t_entry

        out = np.zeros(n, dtype=bool)
        failed_lanes: set = set()
        try:
            for seg_i, (a, b, lane_i) in enumerate(bounds):
                lane = lane_of(lane_i)
                nxt = seg_i + window
                if nxt < len(bounds):
                    a2, b2, lane_i2 = bounds[nxt]
                    recs[nxt], futs[nxt] = submit(nxt, a2, b2,
                                                  lane_of(lane_i2))
                tried = set()
                while True:
                    t_wait0 = time.perf_counter()
                    try:
                        dev, ok = futs[seg_i].result()
                        arr = np.asarray(dev)
                    except Exception as e:
                        recs[seg_i].abandon()
                        tried.add(lane.label)
                        failed_lanes.add(lane.label)
                        lane.breaker.record_failure()
                        self.stats["lane_errors"] += 1
                        logger.warning(
                            "device lane %s failed segment %d/%d (n=%d): "
                            "%s — re-sharding to a healthy peer",
                            lane.label, seg_i, len(bounds), b - a, e)
                        lane = self._next_lane(tried)
                        if lane is None:
                            raise AllLanesFailed(
                                f"segment {seg_i} failed on every healthy "
                                f"lane ({sorted(tried)})") from e
                        self.stats["resharded_segments"] += 1
                        recs[seg_i], futs[seg_i] = submit(seg_i, a, b, lane)
                        continue
                    recs[seg_i].fetched(
                        wait_s=time.perf_counter() - t_wait0)
                    if lane.label not in failed_lanes:
                        lane.breaker.record_success()
                    out[a:b] = arr.reshape(-1)[:b - a] & ok
                    break
        finally:
            for r in all_recs:
                r.abandon()  # no-op for fetched records
        phases.observe_overlap(recs)
        self.stats["calls"] += 1
        self.stats["sigs"] += n
        return out

    def _next_lane(self, tried: set) -> Optional[DeviceLane]:
        """The next healthy lane not already tried for this segment."""
        for lane in self.lanes:
            if lane.label in tried:
                continue
            if lane.breaker.allow():
                return lane
        return None

    @staticmethod
    def _run_lane(lane: DeviceLane, rec, pks, msgs, sigs, chunk,
                  columns):
        """One segment on its lane's worker: per-lane chaos site, pack
        into the worker's scratch, commit to the lane's device, dispatch
        async. Runs on the lane thread; the coordinating thread fetches."""
        faults.inject(LANE_SITE_PREFIX + lane.label)
        return V._run_dispatch(rec, pks, msgs, sigs, chunk,
                               device=lane.device, columns=columns)

    def shutdown(self) -> None:
        for lane in self.lanes:
            lane.shutdown()


# -- the process pool ---------------------------------------------------------

_POOL: Optional[MultiDeviceStream] = None
_POOL_RESOLVED = False
_POOL_LOCK = threading.Lock()


def pool() -> Optional[MultiDeviceStream]:
    """The process-wide MultiDeviceStream, built lazily from jax.devices()
    and TMTPU_VERIFY_DEVICES. None when fewer than two devices are in
    play (or the env knob disables the pool)."""
    global _POOL, _POOL_RESOLVED
    if _POOL_RESOLVED:
        return _POOL
    with _POOL_LOCK:
        if _POOL_RESOLVED:
            return _POOL
        built = None
        try:
            env = os.environ.get(ENV_DEVICES)
            want = int(env) if env else None
            if want is None or want > 1:
                devs = jax.devices()
                count = len(devs) if want is None else min(want, len(devs))
                if count > 1:
                    built = MultiDeviceStream(devices=devs[:count])
                    logger.info(
                        "multi-device verify pool: %d lanes (%s)", count,
                        ", ".join(l.label for l in built.lanes))
        except Exception as e:  # no backend, bad env value, ...
            logger.warning("multi-device pool unavailable: %s", e)
        _POOL = built
        _POOL_RESOLVED = True
        return _POOL


def reset_pool() -> None:
    """Tear down the pool (tests / env-knob changes re-resolve lazily)."""
    global _POOL, _POOL_RESOLVED
    with _POOL_LOCK:
        if _POOL is not None:
            _POOL.shutdown()
        _POOL = None
        _POOL_RESOLVED = False


@contextlib.contextmanager
def disabled():
    """Force the single-device path inside the block (bench A/B runs and
    parity tests measure 'what would this cost without the pool')."""
    global _POOL, _POOL_RESOLVED
    with _POOL_LOCK:
        prev = (_POOL, _POOL_RESOLVED)
        _POOL, _POOL_RESOLVED = None, True
    try:
        yield
    finally:
        with _POOL_LOCK:
            _POOL, _POOL_RESOLVED = prev
