"""Batched SHA-512 on device (u32-pair emulation of u64).

The Ed25519 challenge hash h = SHA-512(R || A || M) is ~0.2% of the verify
kernel's arithmetic, but hashing on the *host* (hashlib loop) costs more
wall-clock than the whole device kernel at stream batch sizes. Moving the
hash on-device makes host prep pure byte packing.

TPU has no u64: every 64-bit word is an (hi, lo) pair of uint32 arrays, each
shaped (*batch,). Carries come from the wraparound compare ``lo_sum < lo_a``
(exact for two-operand adds). Rotations with static shift counts compile to
plain vector shifts.

Replaces the host-side hashing half of the reference's hot call
(crypto/ed25519/ed25519.go:148-155 — Go hashes with crypto/sha512 then calls
edwards25519); differential tests pin this to hashlib.sha512.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# --- constants -------------------------------------------------------------

_K64 = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f, 0xe9b5dba58189dbbc,
    0x3956c25bf348b538, 0x59f111f1b605d019, 0x923f82a4af194f9b, 0xab1c5ed5da6d8118,
    0xd807aa98a3030242, 0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235, 0xc19bf174cf692694,
    0xe49b69c19ef14ad2, 0xefbe4786384f25e3, 0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65,
    0x2de92c6f592b0275, 0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f, 0xbf597fc7beef0ee4,
    0xc6e00bf33da88fc2, 0xd5a79147930aa725, 0x06ca6351e003826f, 0x142929670a0e6e70,
    0x27b70a8546d22ffc, 0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6, 0x92722c851482353b,
    0xa2bfe8a14cf10364, 0xa81a664bbc423001, 0xc24b8b70d0f89791, 0xc76c51a30654be30,
    0xd192e819d6ef5218, 0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99, 0x34b0bcb5e19b48a8,
    0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb, 0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3,
    0x748f82ee5defb2fc, 0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915, 0xc67178f2e372532b,
    0xca273eceea26619c, 0xd186b8c721c0c207, 0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178,
    0x06f067aa72176fba, 0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc, 0x431d67c49c100d4c,
    0x4cc5d4becb3e42b6, 0x597f299cfc657e2a, 0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]
_K_HI = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)

_IV64 = [
    0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b, 0xa54ff53a5f1d36f1,
    0x510e527fade682d1, 0x9b05688c2b3e6c1f, 0x1f83d9abfb41bd6b, 0x5be0cd19137e2179,
]


# --- u64-as-u32-pair primitives (static shift counts) -----------------------

def _add64(ah, al, bh, bl):
    l = al + bl
    c = (l < al).astype(jnp.uint32)
    return ah + bh + c, l


def _rotr(h, l, n: int):
    if n == 32:
        return l, h
    if n < 32:
        return ((h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n)))
    m = n - 32
    return ((l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m)))


def _shr(h, l, n: int):
    # n < 32 for every SHA-512 use (6 and 7)
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return (a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1])


def _small_sigma0(h, l):
    return _xor3(_rotr(h, l, 1), _rotr(h, l, 8), _shr(h, l, 7))


def _small_sigma1(h, l):
    return _xor3(_rotr(h, l, 19), _rotr(h, l, 61), _shr(h, l, 6))


def _big_sigma0(h, l):
    return _xor3(_rotr(h, l, 28), _rotr(h, l, 34), _rotr(h, l, 39))


def _big_sigma1(h, l):
    return _xor3(_rotr(h, l, 14), _rotr(h, l, 18), _rotr(h, l, 41))


# --- compression -----------------------------------------------------------

def _compress(state, block):
    """state (8, 2, *batch) u32; block (32, *batch) u32 big-endian words.

    block[2t] / block[2t+1] are the hi/lo halves of message u64 t.
    """
    batch_shape = block.shape[1:]

    # message schedule: W (80, 2, *batch), built with a fori_loop
    w_init = jnp.zeros((80, 2) + batch_shape, dtype=jnp.uint32)
    w_init = w_init.at[:16, 0].set(block[0::2]).at[:16, 1].set(block[1::2])

    def w_body(t, w):
        w2 = w[t - 2]
        w7 = w[t - 7]
        w15 = w[t - 15]
        w16 = w[t - 16]
        s1h, s1l = _small_sigma1(w2[0], w2[1])
        s0h, s0l = _small_sigma0(w15[0], w15[1])
        h, l = _add64(s1h, s1l, w7[0], w7[1])
        h, l = _add64(h, l, s0h, s0l)
        h, l = _add64(h, l, w16[0], w16[1])
        return w.at[t, 0].set(h).at[t, 1].set(l)

    w = jax.lax.fori_loop(16, 80, w_body, w_init)

    k_hi = jnp.asarray(_K_HI.reshape((80,) + (1,) * len(batch_shape)))
    k_lo = jnp.asarray(_K_LO.reshape((80,) + (1,) * len(batch_shape)))

    def round_body(t, vs):
        ah, al, bh, bl, ch, cl, dh, dl, eh, el, fh, fl, gh, gl, hh, hl = vs
        s1h, s1l = _big_sigma1(eh, el)
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1h, t1l = _add64(hh, hl, s1h, s1l)
        t1h, t1l = _add64(t1h, t1l, chh, chl)
        kh = jax.lax.dynamic_index_in_dim(k_hi, t, 0, keepdims=False)
        kl = jax.lax.dynamic_index_in_dim(k_lo, t, 0, keepdims=False)
        t1h, t1l = _add64(t1h, t1l, kh, kl)
        wt = jax.lax.dynamic_index_in_dim(w, t, 0, keepdims=False)
        t1h, t1l = _add64(t1h, t1l, wt[0], wt[1])
        s0h, s0l = _big_sigma0(ah, al)
        mjh = (ah & bh) ^ (ah & ch) ^ (bh & ch)
        mjl = (al & bl) ^ (al & cl) ^ (bl & cl)
        t2h, t2l = _add64(s0h, s0l, mjh, mjl)
        neh, nel = _add64(dh, dl, t1h, t1l)
        nah, nal = _add64(t1h, t1l, t2h, t2l)
        return (nah, nal, ah, al, bh, bl, ch, cl, neh, nel, eh, el, fh, fl, gh, gl)

    init = tuple(state[i, j] for i in range(8) for j in range(2))
    out = jax.lax.fori_loop(0, 80, round_body, init)

    pairs = []
    for i in range(8):
        h, l = _add64(state[i, 0], state[i, 1], out[2 * i], out[2 * i + 1])
        pairs.append(jnp.stack([h, l]))
    return jnp.stack(pairs)


def sha512_blocks(blocks: jnp.ndarray, nblk: jnp.ndarray) -> jnp.ndarray:
    """blocks (NBLK, 32, *batch) u32 BE words; nblk (*batch,) — per-lane block
    count. Lanes with fewer than NBLK blocks freeze their state after their
    last block. Returns the digest as (8, 2, *batch) u32 (hi, lo) u64 words.
    """
    nblocks_static = blocks.shape[0]
    batch_shape = blocks.shape[2:]
    iv = np.zeros((8, 2, 1), dtype=np.uint32)
    for i, v in enumerate(_IV64):
        iv[i, 0, 0] = v >> 32
        iv[i, 1, 0] = v & 0xFFFFFFFF
    state = jnp.broadcast_to(
        jnp.asarray(iv.reshape((8, 2) + (1,) * len(batch_shape))),
        (8, 2) + tuple(batch_shape),
    )
    for b in range(nblocks_static):
        new = _compress(state, blocks[b])
        mask = (jnp.asarray(b, dtype=nblk.dtype) < nblk)
        state = jnp.where(mask[None, None], new, state)
    return state


def digest_le32(state: jnp.ndarray) -> jnp.ndarray:
    """(8, 2, *batch) digest words -> (16, *batch) u32 little-endian words.

    The Ed25519 challenge treats the 64 digest *bytes* as a little-endian
    integer; LE 32-bit word a of that integer is byteswap of BE word a.
    """
    x = state.reshape((16,) + state.shape[2:])  # BE word stream hi0,lo0,hi1,..
    return ((x >> 24) | ((x >> 8) & 0xFF00) | ((x << 8) & 0xFF0000) | (x << 24))
