"""Batched scalar arithmetic mod L = 2^252 + δ on device (radix-2^11, int32).

Reduces the 512-bit SHA-512 challenge to h mod L and emits the 4-bit window
digits the curve kernel consumes. Mirrors the role of Go x/crypto's
ScReduce in the reference hot call (crypto/ed25519/ed25519.go:148-155).

Radix 2^11 is chosen so that cross products of 11-bit limbs (< 2^22) sum
over a 12-limb multiplicand without approaching the int32 limit, letting
the fold products accumulate with no intermediate carries.

Fold identity: 2^253 ≡ -2δ (mod L), δ = L - 2^252 < 2^125. Splitting a
value at bit 253 (limb 23, since 23·11 = 253) gives h ≡ lo - hi·2δ. Three
folds take 517 bits down to < 2^253 in magnitude (signed); adding 8L and
four conditional subtractions (8L, 4L, 2L, L) then land in [0, L).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ed25519 import L as L_INT

RADIX = 11
MASK = (1 << RADIX) - 1
NL = 23                      # 23 * 11 = 253 bits: fold boundary
DELTA2_INT = 2 * (L_INT - 2**252)


def _int_to_limbs11(x: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = (x >> (RADIX * i)) & MASK
    return out


_D2 = _int_to_limbs11(DELTA2_INT, 12)          # 2δ < 2^126: 12 limbs
_L_MULTS = {m: _int_to_limbs11(m * L_INT, 24) for m in (8, 4, 2, 1)}


def le32_to_limbs11(words: jnp.ndarray, nlimbs: int) -> list:
    """(16, *batch) u32 LE words -> list of nlimbs (*batch,) int32 limbs."""
    out = []
    nwords = words.shape[0]
    for k in range(nlimbs):
        bit = RADIX * k
        w, off = bit // 32, bit % 32
        v = words[w] >> off
        if off > 32 - RADIX and w + 1 < nwords:
            v = v | (words[w + 1] << (32 - off))
        out.append((v & MASK).astype(jnp.int32))
    return out


def _signed_carry(limbs: list) -> list:
    """Sequential signed carry; all limbs land in [0, 2^11) except the top,
    which keeps the sign of the overall value."""
    out = list(limbs)
    for i in range(len(out) - 1):
        c = out[i] >> RADIX          # arithmetic shift: floor division
        out[i] = out[i] - (c << RADIX)
        out[i + 1] = out[i + 1] + c
    return out


def _fold(limbs: list) -> list:
    """limbs (len > NL, carry-normalized, top limb signed) -> lo - hi·2δ."""
    lo = limbs[:NL]
    hi = limbs[NL:]
    ncols = len(hi) + len(_D2) - 1
    cols = [None] * ncols
    for i, h in enumerate(hi):
        for j, d in enumerate(_D2):
            if int(d) == 0:
                continue
            t = h * np.int32(d)
            cols[i + j] = t if cols[i + j] is None else cols[i + j] + t
    # keep ≥ NL+1 limbs so the carry pushes any excess above bit 253 into
    # limb NL, where the next fold's split can see it
    n = max(NL + 1, ncols)
    out = []
    for k in range(n):
        v = lo[k] if k < NL else None
        c = cols[k] if k < ncols and cols[k] is not None else None
        if v is None and c is None:
            out.append(jnp.zeros_like(lo[0]))
        elif c is None:
            out.append(v)
        elif v is None:
            out.append(-c)
        else:
            out.append(v - c)
    return _signed_carry(out)


def _cond_sub(limbs: list, sub: np.ndarray) -> list:
    """limbs (24, carry-normalized ≥ 0) -= sub if limbs >= sub (borrow probe)."""
    d = [limbs[i] - np.int32(sub[i]) for i in range(len(limbs))]
    for i in range(len(d) - 1):
        borrow = (d[i] < 0).astype(jnp.int32)
        d[i] = d[i] + (borrow << RADIX)
        d[i + 1] = d[i + 1] - borrow
    take = d[-1] >= 0
    return [jnp.where(take, d[i], limbs[i]) for i in range(len(limbs))]


def sc_reduce_digits(words: jnp.ndarray) -> jnp.ndarray:
    """(16, *batch) u32 LE words of a 512-bit integer -> (64, *batch) u32
    4-bit window digits of (value mod L), LSB window first."""
    limbs = le32_to_limbs11(words, 47)          # 517 bits ≥ 512
    x = _fold(limbs)                            # ≤ 35 limbs, |x| < 2^386
    x = _fold(x)                                # |x| < 2^259
    if len(x) < NL + 1:
        x = x + [jnp.zeros_like(x[0])] * (NL + 1 - len(x))
    x = _fold(x)                                # |x| < 2^253
    # normalize to [0, L): add 8L, then conditionally subtract 8L,4L,2L,L
    eightL = _int_to_limbs11(8 * L_INT, 24)
    if len(x) < 24:
        x = x + [jnp.zeros_like(x[0])] * (24 - len(x))
    x = _signed_carry([x[i] + np.int32(eightL[i]) for i in range(24)])
    for m in (8, 4, 2, 1):
        x = _cond_sub(x, _L_MULTS[m])
    return limbs11_to_digits(x)


def limbs11_to_digits(limbs: list) -> jnp.ndarray:
    """23+ canonical limbs (< L) -> (64, *batch) u32 nibble digits."""
    digs = []
    for nib in range(64):
        bit = 4 * nib
        a, off = bit // RADIX, bit % RADIX
        v = limbs[a] >> off
        if off > RADIX - 4 and a + 1 < len(limbs):
            v = v | (limbs[a + 1] << (RADIX - off))
        digs.append((v & 15).astype(jnp.uint32))
    return jnp.stack(digs)
