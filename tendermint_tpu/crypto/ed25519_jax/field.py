"""GF(2^255-19) arithmetic for TPU, in radix-2^15 with 17 uint32 limbs.

Design notes (why this representation):

* TPU int32 multiply returns the low 32 bits only — no widening multiply and
  no fast int64. So limb products must fit in 32 bits *exactly*: with 15-bit
  limbs (plus redundancy up to 2^15+57 after the parallel carry), products
  are < 2^31.
* 17 limbs x 15 bits = 255 bits exactly, so the modular fold is aligned:
  2^255 ≡ 19 (mod p) means column j+17 of a product folds into column j with
  a single multiply by 19 — no sub-limb shifting.
* Field elements are shaped ``(17, *batch)``; the verify kernel uses
  ``(17, N//128, 128)`` so per-limb slices land on full (8,128) vregs —
  a flat ``(17, N)`` layout wastes 7/8 of every sublane on per-limb ops.
* Carries are TWO data-parallel passes over all limbs (mask/shift/roll/add),
  not a 17-step sequential chain: after column sums < 2^26, pass one leaves
  limbs < 2^16.4, pass two < 2^15+57 — inside the mul input invariant.

Invariant: limbs entering :func:`mul` are ``<= 2^15 + 57`` (guaranteed by
:func:`carry`); products then stay < 2^31 and split column sums < 2^22.

This replaces the scalar big-int arithmetic inside Go's x/crypto ed25519
(reference crypto/ed25519/ed25519.go:148-155 → filippo.io/edwards25519 field)
with a batched formulation; semantics are tested differentially against
tendermint_tpu.crypto.ed25519.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMBS = 17
RADIX = 15
MASK = (1 << RADIX) - 1  # 0x7FFF

P_INT = 2**255 - 19

# p in limb form: limb0 = 2^15-19, limbs 1..16 = 2^15-1
P_LIMBS = np.array([MASK - 18] + [MASK] * 16, dtype=np.uint32)
# 2p in per-limb form with headroom for lazy subtraction: a + TWO_P - b >= 0
# whenever b is carry-normalized (limbs <= 2^15+57 < 2^16-38).
TWO_P_LIMBS = (P_LIMBS * 2).astype(np.uint32)


# --- host-side packing helpers (numpy) ------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.uint32)
    for i in range(NLIMBS):
        out[i] = (x >> (RADIX * i)) & MASK
    return out


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(a[i]) << (RADIX * i) for i in range(len(a)))


def bytes_to_limbs(b: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian -> (17, N) uint32 limbs of the low 255 bits.

    The caller strips/keeps bit 255 (the x-sign bit) beforehand.
    """
    b = np.asarray(b, dtype=np.uint8)
    n = b.shape[0]
    padded = np.zeros((n, 34), dtype=np.uint32)
    padded[:, :32] = b
    out = np.zeros((NLIMBS, n), dtype=np.uint32)
    for i in range(NLIMBS):
        o = RADIX * i
        byte, shift = o // 8, o % 8
        word = padded[:, byte] | (padded[:, byte + 1] << 8) | (padded[:, byte + 2] << 16)
        out[i] = (word >> shift) & MASK
    out[16] &= (1 << 15) - 1
    return out


def limbs_to_bytes(a: np.ndarray) -> np.ndarray:
    """(17, N) canonical limbs -> (N, 32) uint8 little-endian."""
    a = np.asarray(a, dtype=np.uint64)
    n = a.shape[1]
    vals = np.zeros((n, 32), dtype=np.uint8)
    acc = np.zeros(n, dtype=object)
    for i in range(NLIMBS - 1, -1, -1):
        acc = (acc << RADIX) | a[i]
    for j in range(32):
        vals[:, j] = (acc & 0xFF).astype(np.uint8)
        acc >>= 8
    return vals


# --- device constants ------------------------------------------------------

def const(x: int, batch_ndim: int = 1) -> jnp.ndarray:
    """A field constant shaped (17, 1, ..) broadcasting over the batch dims."""
    shape = (NLIMBS,) + (1,) * batch_ndim
    return jnp.asarray(int_to_limbs(x % P_INT).reshape(shape))


def _bcast(limbs_1d: np.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    shape = (NLIMBS,) + (1,) * (like.ndim - 1)
    return jnp.asarray(limbs_1d.reshape(shape))


# --- core ops --------------------------------------------------------------

def carry(c: jnp.ndarray) -> jnp.ndarray:
    """Parallel carry: column sums (< 2^26 per limb) -> limbs <= 2^15+57.

    Each pass: split every limb into low 15 bits + carry, shift the carries up
    one limb (top carry folds into limb 0 via x19). Two passes bound the
    result: pass 1 leaves limbs < 2^15 + 19*2^11; pass 2 < 2^15 + 57.
    All ops are full-width vector ops over (17, *batch) — no sequential chain.
    """
    c = c.astype(jnp.uint32)
    for _ in range(2):
        lo = c & MASK
        hi = c >> RADIX
        hi_rolled = jnp.concatenate([hi[NLIMBS - 1:] * 19, hi[:NLIMBS - 1]], axis=0)
        c = lo + hi_rolled
    return c


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    two_p = _bcast(TWO_P_LIMBS, a)
    return carry(a + two_p - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    two_p = _bcast(TWO_P_LIMBS, a)
    return carry(two_p - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply. Inputs carry-normalized (limbs <= 2^15+57).

    Columns are accumulated with static-slice scatter-adds
    (``cols.at[i:i+NLIMBS].add``). A jnp.roll-based column build miscompiles
    inside ``lax.fori_loop`` on the TPU backend (verified empirically: valid
    signatures rejected on-device while CPU agrees with the host spec), so
    this MUST stay scatter-based; the differential on-device suite in
    tests/test_tpu_device.py guards it.
    """
    prod = a[:, None] * b[None]                   # (17, 17, *batch), < 2^31
    lo = prod & MASK                              # <= 2^15-1
    hi = prod >> RADIX                            # < 2^16
    batch_shape = prod.shape[2:]
    cols = jnp.zeros((2 * NLIMBS,) + batch_shape, dtype=jnp.uint32)
    for i in range(NLIMBS):
        cols = cols.at[i:i + NLIMBS].add(lo[i])
        cols = cols.at[i + 1:i + 1 + NLIMBS].add(hi[i])
    # fold columns 17.. back with x19 (2^255 ≡ 19): c_j += 19*c_{j+17}
    folded = cols[:NLIMBS] + 19 * cols[NLIMBS:]
    return carry(folded)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Field square: exploits product symmetry (p_ij + p_ji = 2·p_ij) to do
    153 limb products instead of mul's 289 (~35% cheaper on the VPU).

    Cross terms use a pre-doubled operand: a2 = 2a has limbs < 2^16+114, so
    a2_i * a_j < 2^31.1 < 2^32 (uint32-safe); split columns then bound the
    same as :func:`mul`.
    """
    a2 = a + a
    batch_shape = a.shape[1:]
    cols = jnp.zeros((2 * NLIMBS,) + batch_shape, dtype=jnp.uint32)
    for i in range(NLIMBS):
        # row i: diagonal a_i^2 at column 2i, then doubled cross terms
        # a2_i * a_j for j in (i, 17) at columns i+j — one contiguous slice
        row = jnp.concatenate([a[i:i + 1] * a[i:i + 1], a2[i:i + 1] * a[i + 1:]], axis=0)
        lo = row & MASK
        hi = row >> RADIX
        width = NLIMBS - i
        cols = cols.at[2 * i:2 * i + width].add(lo)
        cols = cols.at[2 * i + 1:2 * i + 1 + width].add(hi)
    folded = cols[:NLIMBS] + 19 * cols[NLIMBS:]
    return carry(folded)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^15)."""
    prod = a * jnp.uint32(k)
    lo = prod & MASK
    hi = prod >> RADIX
    hi_rolled = jnp.concatenate([hi[NLIMBS - 1:] * 19, hi[:NLIMBS - 1]], axis=0)
    return carry(lo + hi_rolled)


def _seq_carry(a: jnp.ndarray) -> jnp.ndarray:
    """Exact 17-step sequential carry; top carry folds into limb 0 with x19."""
    limbs = list(jnp.split(a, NLIMBS, axis=0))
    for i in range(NLIMBS - 1):
        c = limbs[i] >> RADIX
        limbs[i] = limbs[i] & MASK
        limbs[i + 1] = limbs[i + 1] + c
    top = limbs[16] >> RADIX
    limbs[16] = limbs[16] & MASK
    limbs[0] = limbs[0] + top * 19
    return jnp.concatenate(limbs, axis=0)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce to the canonical representative in [0, p); limbs strictly 15-bit."""
    # Two parallel passes settle the bulk redundancy, then exact sequential
    # passes guarantee strictly-15-bit limbs (a purely parallel chain can
    # leave a limb >= 2^15 when a carry must walk through a run of 0x7fff
    # limbs — representation-dependent eq()/is_zero() otherwise).
    a = carry(carry(a))
    a = _seq_carry(a)
    a = _seq_carry(a)
    a = _seq_carry(a)
    a = _seq_carry(a)
    # now limbs strictly 15-bit, value < 2^255 < 2p: conditionally subtract p
    # once (sequential borrow chain, but freeze runs only a handful of times)
    p = _bcast(P_LIMBS, a)
    d = list(jnp.split(a.astype(jnp.int32) - p.astype(jnp.int32), NLIMBS, axis=0))
    for i in range(NLIMBS - 1):
        borrow = (d[i] >> 31) & 1          # 1 if negative
        d[i] = d[i] + (borrow << RADIX)
        d[i + 1] = d[i + 1] - borrow
    final_borrow = (d[16] >> 31) & 1
    d[16] = d[16] + (final_borrow << RADIX)
    diff = jnp.concatenate(d, axis=0)
    ge_p = (final_borrow == 0)             # a >= p
    return jnp.where(ge_p, diff.astype(jnp.uint32), a)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """(*batch,) bool: a ≡ 0 (mod p)."""
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(*batch,) bool: a ≡ b (mod p)."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """(*batch,) uint32: low bit of the canonical representative."""
    return freeze(a)[0] & 1


# --- exponentiation chains -------------------------------------------------

def _sqr_n(a: jnp.ndarray, n: int) -> jnp.ndarray:
    return jax.lax.fori_loop(0, n, lambda _, x: sqr(x), a)


def _pow_2250_minus_1(z: jnp.ndarray):
    """z^(2^250 - 1) plus intermediates needed by callers (ref10 chain)."""
    z2 = sqr(z)                            # 2
    z9 = mul(_sqr_n(z2, 2), z)             # 9
    z11 = mul(z9, z2)                      # 11
    z_5_0 = mul(sqr(z11), z9)              # 2^5 - 1
    z_10_0 = mul(_sqr_n(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(_sqr_n(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sqr_n(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sqr_n(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sqr_n(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sqr_n(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sqr_n(z_200_0, 50), z_50_0)
    return z_250_0, z11


def inverse(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21); returns 0 for z = 0."""
    z_250_0, z11 = _pow_2250_minus_1(z)
    return mul(_sqr_n(z_250_0, 5), z11)


def pow_p58(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3)."""
    z_250_0, _ = _pow_2250_minus_1(z)
    return mul(_sqr_n(z_250_0, 2), z)
