"""GF(2^255-19) arithmetic for TPU, in radix-2^15 with 17 uint32 limbs.

Design notes (why this representation):

* TPU int32 multiply returns the low 32 bits only — no widening multiply and
  no fast int64. So limb products must fit in 32 bits *exactly*: with 15-bit
  limbs (plus redundancy up to 2^15+2 after carries), products are < 2^31.
* 17 limbs x 15 bits = 255 bits exactly, so the modular fold is aligned:
  2^255 ≡ 19 (mod p) means column j+17 of a product folds into column j with
  a single multiply by 19 — no sub-limb shifting.
* Every field element is shaped ``(17, N)`` (limb index leading, batch in the
  trailing dim) so the batch rides the 128-wide VPU lanes and limb-indexed
  slicing is cheap.

Invariant: limbs entering :func:`mul` are ``<= 2^15 + 2`` (guaranteed by
:func:`carry`). All ops are jit/vmap-free pure jnp and shape-polymorphic in N.

This replaces the scalar big-int arithmetic inside Go's x/crypto ed25519
(reference crypto/ed25519/ed25519.go:148-155 → filippo.io/edwards25519 field)
with a batched formulation; semantics are tested differentially against
tendermint_tpu.crypto.ed25519.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMBS = 17
RADIX = 15
MASK = (1 << RADIX) - 1  # 0x7FFF

P_INT = 2**255 - 19

# p in limb form: limb0 = 2^15-19, limbs 1..16 = 2^15-1
P_LIMBS = np.array([MASK - 18] + [MASK] * 16, dtype=np.uint32)
# 2p in per-limb form with headroom for lazy subtraction: a + TWO_P - b >= 0
# whenever b is carry-normalized (limbs <= 2^15+2 < 2^16-2).
TWO_P_LIMBS = (P_LIMBS * 2).astype(np.uint32)


# --- host-side packing helpers (numpy) ------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.uint32)
    for i in range(NLIMBS):
        out[i] = (x >> (RADIX * i)) & MASK
    return out


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(a[i]) << (RADIX * i) for i in range(len(a)))


def bytes_to_limbs(b: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian -> (17, N) uint32 limbs of the low 255 bits.

    The caller strips/keeps bit 255 (the x-sign bit) beforehand.
    """
    b = np.asarray(b, dtype=np.uint8)
    n = b.shape[0]
    padded = np.zeros((n, 34), dtype=np.uint32)
    padded[:, :32] = b
    out = np.zeros((NLIMBS, n), dtype=np.uint32)
    for i in range(NLIMBS):
        o = RADIX * i
        byte, shift = o // 8, o % 8
        word = padded[:, byte] | (padded[:, byte + 1] << 8) | (padded[:, byte + 2] << 16)
        out[i] = (word >> shift) & MASK
    out[16] &= (1 << 15) - 1
    return out


def limbs_to_bytes(a: np.ndarray) -> np.ndarray:
    """(17, N) canonical limbs -> (N, 32) uint8 little-endian."""
    a = np.asarray(a, dtype=np.uint64)
    n = a.shape[1]
    vals = np.zeros((n, 32), dtype=np.uint8)
    acc = np.zeros(n, dtype=object)
    for i in range(NLIMBS - 1, -1, -1):
        acc = (acc << RADIX) | a[i]
    for j in range(32):
        vals[:, j] = (acc & 0xFF).astype(np.uint8)
        acc >>= 8
    return vals


# --- device constants ------------------------------------------------------

def const(x: int) -> jnp.ndarray:
    """A field constant as a (17, 1) device array (broadcasts over batch)."""
    return jnp.asarray(int_to_limbs(x % P_INT).reshape(NLIMBS, 1))


# --- core ops --------------------------------------------------------------

def carry(c: jnp.ndarray) -> jnp.ndarray:
    """Carry-propagate column sums (< 2^26 per limb) to limbs <= 2^15+2.

    One full sequential pass, fold the >=2^255 overflow back via x19, then one
    extra step limb0->limb1. Post-condition: limb0 < 2^15, limb1 <= 2^15+2,
    limbs 2..16 < 2^15 — all safe as mul inputs.
    """
    c = list(jnp.split(c.astype(jnp.uint32), NLIMBS, axis=0))
    for i in range(NLIMBS - 1):
        c[i + 1] = c[i + 1] + (c[i] >> RADIX)
        c[i] = c[i] & MASK
    top = c[16] >> RADIX
    c[16] = c[16] & MASK
    c[0] = c[0] + 19 * top
    c[1] = c[1] + (c[0] >> RADIX)
    c[0] = c[0] & MASK
    return jnp.concatenate(c, axis=0)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    two_p = jnp.asarray(TWO_P_LIMBS.reshape(NLIMBS, 1))
    return carry(a + two_p - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    two_p = jnp.asarray(TWO_P_LIMBS.reshape(NLIMBS, 1))
    return carry(two_p - a + jnp.zeros_like(a))


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply. Inputs carry-normalized (limbs <= 2^15+2)."""
    # outer products, split into 15-bit halves so column sums stay < 2^26
    prod = a[:, None, :] * b[None, :, :]          # (17, 17, N), each < 2^31
    lo = prod & MASK
    hi = prod >> RADIX
    batch_shape = prod.shape[2:]
    cols = jnp.zeros((2 * NLIMBS, ) + batch_shape, dtype=jnp.uint32)
    for i in range(NLIMBS):
        cols = cols.at[i:i + NLIMBS].add(lo[i])
        cols = cols.at[i + 1:i + 1 + NLIMBS].add(hi[i])
    # fold columns 17..33 back with x19 (2^255 ≡ 19): c_j += 19*c_{j+17}
    folded = cols[:NLIMBS] + 19 * cols[NLIMBS:]
    return carry(folded)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^15)."""
    prod = a * jnp.uint32(k)
    lo = prod & MASK
    hi = prod >> RADIX
    cols = jnp.zeros((NLIMBS + 1,) + a.shape[1:], dtype=jnp.uint32).at[:NLIMBS].add(lo)
    cols = cols.at[1:NLIMBS + 1].add(hi)
    folded = cols[:NLIMBS].at[0].add(19 * cols[NLIMBS])
    return carry(folded)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce to the canonical representative in [0, p); limbs strictly 15-bit."""
    # Repeated passes settle redundancy: after pass 2 the value is
    # < 2^255 + 2^241; pass 3 folds any remaining >=2^255 excess; pass 4 runs
    # with no fold and leaves every limb strictly 15-bit. (Each pass is 18
    # cheap vector ops; freeze runs only ~4x per verification.)
    a = carry(a)
    a = carry(a)
    a = carry(a)
    a = carry(a)
    # now value < 2^255, limbs < 2^15 strictly; conditionally subtract p once
    p = jnp.asarray(P_LIMBS.reshape(NLIMBS, 1))
    d = list(jnp.split(a.astype(jnp.int32) - p.astype(jnp.int32), NLIMBS, axis=0))
    for i in range(NLIMBS - 1):
        borrow = (d[i] >> 31) & 1          # 1 if negative
        d[i] = d[i] + (borrow << RADIX)
        d[i + 1] = d[i + 1] - borrow
    final_borrow = (d[16] >> 31) & 1
    d[16] = d[16] + (final_borrow << RADIX)
    diff = jnp.concatenate(d, axis=0)
    ge_p = (final_borrow == 0)             # a >= p
    return jnp.where(ge_p, diff.astype(jnp.uint32), a)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool: a ≡ 0 (mod p)."""
    return jnp.all(freeze(a) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(N,) bool: a ≡ b (mod p)."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """(N,) uint32: low bit of the canonical representative."""
    return freeze(a)[0] & 1


# --- exponentiation chains -------------------------------------------------

def _sqr_n(a: jnp.ndarray, n: int) -> jnp.ndarray:
    return jax.lax.fori_loop(0, n, lambda _, x: sqr(x), a)


def _pow_2250_minus_1(z: jnp.ndarray):
    """z^(2^250 - 1) plus intermediates needed by callers (ref10 chain)."""
    z2 = sqr(z)                            # 2
    z9 = mul(_sqr_n(z2, 2), z)             # 9
    z11 = mul(z9, z2)                      # 11
    z_5_0 = mul(sqr(z11), z9)              # 2^5 - 1
    z_10_0 = mul(_sqr_n(z_5_0, 5), z_5_0)  # 2^10 - 1
    z_20_0 = mul(_sqr_n(z_10_0, 10), z_10_0)
    z_40_0 = mul(_sqr_n(z_20_0, 20), z_20_0)
    z_50_0 = mul(_sqr_n(z_40_0, 10), z_10_0)
    z_100_0 = mul(_sqr_n(z_50_0, 50), z_50_0)
    z_200_0 = mul(_sqr_n(z_100_0, 100), z_100_0)
    z_250_0 = mul(_sqr_n(z_200_0, 50), z_50_0)
    return z_250_0, z11


def inverse(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21); returns 0 for z = 0."""
    z_250_0, z11 = _pow_2250_minus_1(z)
    return mul(_sqr_n(z_250_0, 5), z11)


def pow_p58(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3)."""
    z_250_0, _ = _pow_2250_minus_1(z)
    return mul(_sqr_n(z_250_0, 2), z)
