"""Batched TPU Ed25519 verification (JAX): the framework's north-star kernel.

See field.py (GF(2^255-19) limb arithmetic), curve.py (batched group ops),
verify.py (host prep + jitted verification kernel).
"""

from .verify import batch_verify, prepare_batch, pack_device_inputs  # noqa: F401
