"""Batched TPU Ed25519 verification (JAX): the framework's north-star kernel.

See field.py (GF(2^255-19) limb arithmetic), curve.py (batched group ops),
verify.py (host prep + jitted verification kernel).
"""

from .verify import (  # noqa: F401
    batch_verify,
    batch_verify_stream,
    pack_device_inputs,
    prepare_batch,
)
