"""Batched edwards25519 group operations on TPU.

Points are extended homogeneous coordinates (X, Y, Z, T) with X*Y = Z*T —
each coordinate a ``(17, N)`` field element (see field.py). The addition law
used is the complete a=-1 twisted-Edwards formula set (valid for *all* input
pairs, including doubling and identity, because -1 is square and d non-square
mod 2^255-19), so the batched scalar-mult has no data-dependent branches —
exactly what the TPU VPU wants.

Replaces the scalar group logic reached from the reference's
crypto/ed25519/ed25519.go:148-155 (via Go's edwards25519) with a batched
formulation.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from . import field as F

# curve constants (single source of truth: the host spec module)
from ..ed25519 import D as D_INT, SQRT_M1 as SQRT_M1_INT  # noqa: E402

D2_INT = (2 * D_INT) % F.P_INT

# NOTE on fori_loop unrolling: unroll>1 measured ~2x faster on an isolated
# field-mul loop but consistently SLOWER on the full verify kernel (compile
# blowup/VMEM pressure), so the loops below deliberately stay unroll=1.


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(batch_shape) -> Point:
    if isinstance(batch_shape, int):
        batch_shape = (batch_shape,)
    zero = jnp.zeros((F.NLIMBS,) + tuple(batch_shape), dtype=jnp.uint32)
    one = zero.at[0].set(1)
    return Point(zero, one, one, zero)


def add(p: Point, q: Point) -> Point:
    """Complete extended addition (2*d variant), ~9 field muls."""
    x, y, z, e, h = _add_xyz(p, q)
    return Point(x, y, z, F.mul(e, h))


def _add_xyz(p: Point, q: Point):
    """Complete addition without the T output (8M): T = E*H is only needed
    when the *next* op reads it — callers multiply the returned (e, h) pair
    on demand (same deferral pattern as _dbl_xyz)."""
    d2 = F.const(D2_INT, p.x.ndim - 1)
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, q.t), d2)
    dd = F.mul(p.z, q.z)
    dd = F.add(dd, dd)
    e = F.sub(b, a)
    f = F.sub(dd, c)
    g = F.add(dd, c)
    h = F.add(b, a)
    return F.mul(e, f), F.mul(g, h), F.mul(f, g), e, h


def dbl(p: Point) -> Point:
    """Doubling, 4M + 4S (mirrors the host _pt_dbl formulas exactly)."""
    x, y, z, e, h = _dbl_xyz(p)
    return Point(x, y, z, F.mul(e, h))


def _dbl_xyz(p: Point):
    """Doubling without the T output (3M + 4S): doubling never *reads* T, so
    chains of doublings only need the final T — callers multiply the returned
    (e, h) factors when (and only when) the next op consumes T."""
    a = F.sqr(p.x)
    b = F.sqr(p.y)
    c = F.sqr(p.z)
    c = F.add(c, c)
    h = F.add(a, b)
    xy = F.add(p.x, p.y)
    e = F.sub(h, F.sqr(xy))
    g = F.sub(a, b)
    f = F.add(c, g)
    return F.mul(e, f), F.mul(g, h), F.mul(f, g), e, h


def neg(p: Point) -> Point:
    return Point(F.neg(p.x), p.y, p.z, F.neg(p.t))


class Niels(NamedTuple):
    """Precomputed affine point: (y+x, y-x, 2*d*x*y). Identity = (1, 1, 0)."""
    yplusx: jnp.ndarray
    yminusx: jnp.ndarray
    t2d: jnp.ndarray


def add_niels(p: Point, n: Niels) -> Point:
    """Mixed addition with a precomputed affine point, ~7 field muls."""
    a = F.mul(F.sub(p.y, p.x), n.yminusx)
    b = F.mul(F.add(p.y, p.x), n.yplusx)
    c = F.mul(p.t, n.t2d)
    dd = F.add(p.z, p.z)
    e = F.sub(b, a)
    f = F.sub(dd, c)
    g = F.add(dd, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


# --- decompression (RFC 8032 §5.1.3) ---------------------------------------

def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """(17,N) y limbs (bit 255 already stripped) + (N,) sign -> (Point, ok).

    Rejects y >= p, non-square x^2, and x == 0 with sign 1 — identical rules
    to the host ed25519._recover_x.
    """
    nb = y_limbs.ndim - 1
    one = F.const(1, nb)
    # canonical check: y < p  (freeze is identity for canonical 15-bit input;
    # compare frozen value against the raw input limbs)
    y_ok = jnp.all(F.freeze(y_limbs) == y_limbs, axis=0)

    yy = F.sqr(y_limbs)
    u = F.sub(yy, one)
    v = F.add(F.mul(yy, F.const(D_INT, nb)), one)
    v3 = F.mul(F.sqr(v), v)
    v7 = F.mul(F.sqr(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    vxx = F.mul(v, F.sqr(x))
    # vxx == ±u probed as (vxx ∓ u) == 0: two freezes instead of three
    ok_direct = F.is_zero(F.sub(vxx, u))
    ok_flip = F.is_zero(F.add(vxx, u))
    x = jnp.where(ok_direct, x, F.mul(x, F.const(SQRT_M1_INT, nb)))
    on_curve = ok_direct | ok_flip

    # one freeze of x yields both the zero test and the parity bit
    fx = F.freeze(x)
    x_is_zero = jnp.all(fx == 0, axis=0)
    sign = sign.astype(jnp.uint32)
    ok = y_ok & on_curve & ~(x_is_zero & (sign == 1))
    flip = (fx[0] & 1) != sign
    x = jnp.where(flip, F.neg(x), x)
    pt = Point(x, y_limbs, jnp.zeros_like(x).at[0].set(1), F.mul(x, y_limbs))
    return pt, ok


# --- encoding --------------------------------------------------------------

def encode(p: Point):
    """-> (y_canonical (17,N), sign (N,)): the 32-byte encoding, in limb form.

    Uses the per-element Fermat chain: it is ~95% squarings (cheap via
    F.sqr) at full batch width, and measured FASTER on TPU than a
    Montgomery/product-tree batch inversion, whose narrow tree levels are
    latency-bound (the tree's ~3 muls/element never pay for its 254-mul
    width-1 root chain).
    """
    zinv = F.inverse(p.z)
    x = F.freeze(F.mul(p.x, zinv))
    y = F.freeze(F.mul(p.y, zinv))
    return y, (x[0] & 1)


# --- scalar multiplication -------------------------------------------------

def _select_point(table: Point, digits: jnp.ndarray) -> Point:
    """table coords shaped (16, 17, N); digits (N,) -> Point at digits, per lane.

    Arithmetic one-hot select (predictable on TPU; avoids lane-varying gather).
    """
    oh = (jnp.arange(16, dtype=jnp.uint32).reshape((16,) + (1,) * digits.ndim)
          == digits[None]).astype(jnp.uint32)
    sel = lambda t: jnp.einsum("jl...,j...->l...", t, oh)
    return Point(sel(table.x), sel(table.y), sel(table.z), sel(table.t))


def scalar_mul_windowed(p: Point, digits: jnp.ndarray) -> Point:
    """[k]P where k = sum digits[i] * 16^i, digits (64, N) in [0,16).

    Fixed 4-bit windows: build [0..15]P once (15 complete adds), then
    64 iterations of 4 doublings + one table add. No data-dependent control
    flow; everything is batched across N.

    The inner doublings use the T-free variant (_dbl_xyz): only the 4th
    doubling of each window materializes T (consumed by the table add), and
    the add itself defers its T product to the (e, h) pair carried across
    iterations — 4 fewer field muls per window than the naive chain.
    """
    batch_shape = p.x.shape[1:]
    entries = [identity(batch_shape), p]
    for _ in range(14):
        entries.append(add(entries[-1], p))
    table = Point(*(jnp.stack([getattr(e, c) for e in entries]) for c in ("x", "y", "z", "t")))

    def body(i, carry):
        x, y, z, e_acc, h_acc = carry
        acc = Point(x, y, z, None)
        for k in range(4):
            x, y, z, e, h = _dbl_xyz(acc)
            acc = Point(x, y, z, F.mul(e, h) if k == 3 else None)
        dig = jax.lax.dynamic_index_in_dim(digits, 63 - i, axis=0, keepdims=False)
        q = _select_point(table, dig)
        # complete add, deferring the output T = E*H to the carried pair
        return _add_xyz(acc, q)

    ident = identity(batch_shape)
    init = (ident.x, ident.y, ident.z, ident.x, ident.y)  # e*h = 0*1 = t
    x, y, z, e, h = jax.lax.fori_loop(0, 64, body, init)
    return Point(x, y, z, F.mul(e, h))


# --- fixed-base multiplication ([s]B) --------------------------------------

_BASE_TABLE_CACHE = None


def _build_base_table() -> np.ndarray:
    """(64, 16, 3, 17) uint32: niels form of [j * 16^i]B, built host-side once."""
    from .. import ed25519 as hosted

    P = F.P_INT
    B_ext = (hosted.B[0], hosted.B[1], 1, hosted.B[0] * hosted.B[1] % P)
    rows = []
    base = B_ext
    for _ in range(64):
        acc = hosted._IDENT
        row = []
        for _j in range(16):
            row.append(acc)
            acc = hosted._pt_add(acc, base)
        rows.append(row)
        for _ in range(4):
            base = hosted._pt_dbl(base)
    # batch-invert all Z coords (Montgomery trick)
    flat = [pt for row in rows for pt in row]
    zs = [pt[2] for pt in flat]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    invs = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        invs[i] = prefix[i] * inv_all % P
        inv_all = inv_all * zs[i] % P
    out = np.zeros((64, 16, 3, F.NLIMBS), dtype=np.uint32)
    for idx, pt in enumerate(flat):
        zi = invs[idx]
        x, y = pt[0] * zi % P, pt[1] * zi % P
        i, j = divmod(idx, 16)
        out[i, j, 0] = F.int_to_limbs((y + x) % P)
        out[i, j, 1] = F.int_to_limbs((y - x) % P)
        out[i, j, 2] = F.int_to_limbs(2 * D_INT * x % P * y % P)
    return out


def base_table() -> jnp.ndarray:
    # Cache holds a NUMPY array: caching a jnp array built inside a
    # shard_map/jit trace leaks that trace's tracer into later jits
    # (UnexpectedTracerError). jnp.asarray at the use site is free — XLA
    # interns the constant per-compilation.
    global _BASE_TABLE_CACHE
    if _BASE_TABLE_CACHE is None:
        _BASE_TABLE_CACHE = _build_base_table()
    return jnp.asarray(_BASE_TABLE_CACHE)


def scalar_mul_base(digits: jnp.ndarray) -> Point:
    """[s]B with s = sum digits[i] * 16^i, digits (64, N); 64 mixed adds, no doublings."""
    table = base_table()  # (64, 16, 3, 17)
    batch_shape = digits.shape[1:]

    def body(i, acc):
        row = jax.lax.dynamic_index_in_dim(table, i, axis=0, keepdims=False)  # (16,3,17)
        dig = jax.lax.dynamic_index_in_dim(digits, i, axis=0, keepdims=False)  # (*batch,)
        oh = (jnp.arange(16, dtype=jnp.uint32).reshape((16,) + (1,) * dig.ndim)
              == dig[None]).astype(jnp.uint32)
        ent = jnp.einsum("jcl,j...->cl...", row, oh)  # (3,17,*batch)
        return add_niels(acc, Niels(ent[0], ent[1], ent[2]))

    return jax.lax.fori_loop(0, 64, body, identity(batch_shape))
