"""Batched Ed25519 verification: vectorized host packing + on-device
SHA-512 / scalar reduction / curve arithmetic.

Split of work (SURVEY.md §7 "hard parts"):

* host (numpy, no per-item Python crypto): length checks, the s < L
  canonicality compare, and packing the SHA-512 preimage blocks
  (R || A || M, padded) plus the 32-byte s. R and A are recovered *from the
  first hash block* on device, so per-signature transfer is just the padded
  preimage + s + a block count (~300 B for vote-sized messages);
* device (one jitted call): SHA-512 of the preimage (sha512.py), reduction
  of the 512-bit challenge mod L and window-digit extraction (scalar.py),
  point decompression of A, [h](-A) via batched 4-bit windowed
  double-and-add, [s]B via a precomputed 64x16 niels table, and the final
  encoding/equality decision against R (curve.py).

Two entry points:

* :func:`batch_verify` — one kernel execution, for a single batch;
* :func:`batch_verify_stream` — a ``lax.scan`` over fixed-size chunks inside
  ONE execution. Dispatch of a jitted computation has a large fixed cost on
  remote-attached TPUs (~100 ms through a relay, measured), so sustained
  throughput requires amortizing it over many chunks per call.

Accept/reject decisions are byte-identical to the host spec
(tendermint_tpu.crypto.ed25519.verify, mirroring the reference's Go
x/crypto hot call at crypto/ed25519/ed25519.go:148-155); differential tests
enforce this on valid, corrupted, and adversarial inputs.
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import curve
from . import field as F
from . import scalar as S
from . import sha512 as H
from .. import phases
from ..ed25519 import L

LANE = 128  # batch is reshaped to (B, 128) so per-limb ops fill (8,128) vregs

# L as 4 little-endian u64 words, for the vectorized s < L compare
_L_WORDS = np.frombuffer(L.to_bytes(32, "little"), dtype="<u8").copy()


def _bswap32(x: jnp.ndarray) -> jnp.ndarray:
    return (x >> 24) | ((x >> 8) & 0xFF00) | ((x << 8) & 0xFF0000) | (x << 24)


def _le32_to_limbs15(words) -> jnp.ndarray:
    """8 (*batch,) u32 LE words (top bit already stripped) -> (17, *batch)."""
    out = []
    for k in range(F.NLIMBS):
        bit = F.RADIX * k
        w, off = bit // 32, bit % 32
        v = words[w] >> off
        if off > 32 - F.RADIX and w + 1 < 8:
            v = v | (words[w + 1] << (32 - off))
        out.append(v & F.MASK)
    return jnp.stack(out)


def _word_nibbles(words: jnp.ndarray) -> jnp.ndarray:
    """(8, *batch) u32 LE words -> (64, *batch) 4-bit digits, LSB first."""
    digs = []
    for nib in range(64):
        w, off = nib // 8, (nib % 8) * 4
        digs.append((words[w] >> off) & 15)
    return jnp.stack(digs)


@partial(jax.jit, static_argnums=())
def _verify_kernel(blocks, nblk, s_words):
    """blocks (NBLK, 32, *batch) u32 BE sha words of R||A||M padded;
    nblk (*batch,) i32; s_words (8, *batch) u32 LE. -> (*batch,) bool."""
    le0 = _bswap32(blocks[0])                    # bytes 0..127 as LE32 words
    r_words = [le0[i] for i in range(8)]
    a_words = [le0[8 + i] for i in range(8)]
    a_sign = a_words[7] >> 31
    r_sign = r_words[7] >> 31
    a_words[7] = a_words[7] & 0x7FFFFFFF
    r_words[7] = r_words[7] & 0x7FFFFFFF
    a_y = _le32_to_limbs15(a_words)
    r_y = _le32_to_limbs15(r_words)

    digest = H.sha512_blocks(blocks, nblk)
    h_digits = S.sc_reduce_digits(H.digest_le32(digest))
    s_digits = _word_nibbles(s_words)

    A, ok_a = curve.decompress(a_y, a_sign)
    # failed decompressions leave garbage coordinates that are not on the
    # curve, where the complete addition law's z != 0 guarantee (and hence
    # encode's batch-inversion precondition) does not hold — mask them to the
    # identity; their verdict is already forced false by ok_a.
    ident = curve.identity(a_y.shape[1:])
    A = curve.Point(*(jnp.where(ok_a[None], c, ic)
                      for c, ic in zip(A, ident)))
    h_negA = curve.scalar_mul_windowed(curve.neg(A), h_digits)
    sB = curve.scalar_mul_base(s_digits)
    rprime = curve.add(sB, h_negA)
    y_enc, sign_enc = curve.encode(rprime)
    eq_r = jnp.all(y_enc == r_y, axis=0) & (sign_enc == r_sign)
    return ok_a & eq_r


@partial(jax.jit, static_argnums=())
def _verify_stream_kernel(blocks, nblk, s_words):
    """Scan the verify kernel over K chunks in one execution.

    blocks (K, NBLK, 32, B, 128), nblk (K, B, 128), s_words (K, 8, B, 128).
    """
    def step(_, x):
        b, n, s = x
        return None, _verify_kernel.__wrapped__(b, n, s)

    _, out = jax.lax.scan(step, None, (blocks, nblk, s_words))
    return out


def _assemble_blocks(template, diff_cols, diff_vals, mlen, r_b, a_b):
    """Build SHA-512 preimage words ON DEVICE from a shared message template
    plus per-item sparse diffs.

    The wire format exists because commit/vote batches are highly redundant:
    all sign-bytes in a commit share chain_id/height/round/block_id and
    differ only in a handful of timestamp bytes (types/canonical.go layout).
    Shipping the template once plus the differing columns cuts per-item
    transfer ~2.5x vs dense padded blocks — host->device bandwidth, not
    device compute, is the dominant cost of the batched verifier.

    template (MLEN,) u8; diff_cols (C,) i32; diff_vals (C, *batch) u8;
    mlen (*batch,) i32; r_b/a_b (32, *batch) u8.
    Returns (blocks (NBLK, 32, *batch) u32 BE words, nblk (*batch,) i32),
    byte-identical to prepare_batch's output for the same items.
    """
    mlen_max = template.shape[0]
    batch_shape = mlen.shape
    bcast = (mlen_max,) + (1,) * len(batch_shape)
    m = jnp.broadcast_to(template.reshape(bcast),
                         (mlen_max,) + batch_shape).astype(jnp.uint8)
    if diff_cols.shape[0]:
        m = m.at[diff_cols].set(diff_vals)
    iota = jax.lax.broadcasted_iota(jnp.int32, (mlen_max,) + batch_shape, 0)
    # zero beyond each item's message, then the 0x80 pad marker
    m = jnp.where(iota < mlen[None], m, jnp.uint8(0))
    m = jnp.where(iota == mlen[None], jnp.uint8(0x80), m)
    # 128-bit big-endian bit length occupies the last 8 bytes of the item's
    # last block (bitlen < 2^32 for any message this path handles)
    bitlen = ((mlen + 64) * 8).astype(jnp.uint32)
    nblk = (64 + mlen + 17 + 127) // 128  # derived on device: 4B/sig saved
    last = nblk * 128 - 64  # block end in message coordinates
    for k in range(8):
        byte_k = ((bitlen >> (8 * k)) & 0xFF).astype(jnp.uint8)
        m = jnp.where(iota == (last - 1 - k)[None], byte_k[None], m)
    full = jnp.concatenate([r_b, a_b, m], axis=0)  # (NBLK*128, *batch)
    nblk_max = (mlen_max + 64) // 128
    w = full.reshape((nblk_max, 32, 4) + batch_shape).astype(jnp.uint32)
    words = (w[:, :, 0] << 24) | (w[:, :, 1] << 16) | (w[:, :, 2] << 8) | w[:, :, 3]
    return words, nblk.astype(jnp.int32)


@partial(jax.jit, static_argnums=())
def _verify_sparse_stream_kernel(templates, diff_cols, diff_vals, mlen,
                                 r_b, a_b, s_b):
    """Scan the verify kernel over K chunks, assembling preimage blocks
    on-device from the sparse wire format. Each chunk carries its OWN
    template (a fast-sync window holds several commits whose height /
    block_id / chain bytes differ ACROSS commits but are constant within
    one — per-chunk templates keep the diff-column set to just the
    per-signature bytes).

    templates (K, MLEN) u8; diff_cols (C,) i32; diff_vals (K, C, B, 128) u8;
    mlen (K, B, 128) i32; r_b/a_b/s_b (K, 32, B, 128) u8.
    """
    def step(_, x):
        tpl, dv, ml, rb, ab, sb = x
        blocks, nb = _assemble_blocks(tpl, diff_cols, dv, ml, rb, ab)
        sw = sb.reshape((8, 4) + sb.shape[1:]).astype(jnp.uint32)
        s_words = sw[:, 0] | (sw[:, 1] << 8) | (sw[:, 2] << 16) | (sw[:, 3] << 24)
        return None, _verify_kernel.__wrapped__(blocks, nb, s_words)

    _, out = jax.lax.scan(step, None,
                          (templates, diff_vals, mlen, r_b, a_b, s_b))
    return out


# sparse path pays off when the union of differing message columns is small;
# beyond this, dense blocks transfer less
MAX_SPARSE_COLS = 96


def _c_pad_bucket(c: int) -> int:
    """Diff-column count padded to a bucket so the sparse kernel compiles
    once per bucket, not per batch. ONE ladder for both the row-discovery
    and the columnar pack paths — they must stay shape-compatible or
    equivalent batches would compile twice."""
    return next(cp for cp in (4, 8, 16, 32, 64, MAX_SPARSE_COLS)
                if cp >= max(c, 1))

# content-addressed device residency for the pubkey plane: commit
# verification reuses the SAME validator keys for every block (fast-sync
# replays thousands of commits against one set), so the (K, 32, B, 128)
# key array is uploaded once and referenced by hash afterwards — host->
# device bytes are the dominant cost of the batched verifier. Keyed per
# target device: each lane of the multi-device pool holds its own copy.
_PK_DEVICE_CACHE: "dict" = {}
# sized for a few live validator sets RESIDENT ON EVERY LANE of an
# 8-device pool (entries are per (content, device)); 8 was enough when
# everything ran on chip 0
_PK_CACHE_MAX = 32
_PK_CACHE_LOCK = threading.Lock()


def _device_cached(arr: np.ndarray, device=None):
    import hashlib

    dev_key = None if device is None else (device.platform, device.id)
    key = (hashlib.sha256(arr.tobytes()).digest(), arr.shape,
           str(arr.dtype), dev_key)
    # the lock also dedupes concurrent identical puts from pipeline workers;
    # device_put itself is lazy (transfer happens at first use), so holding
    # it across the put is cheap
    with _PK_CACHE_LOCK:
        hit = _PK_DEVICE_CACHE.get(key)
        if hit is not None:
            return hit
        if len(_PK_DEVICE_CACHE) >= _PK_CACHE_MAX:
            _PK_DEVICE_CACHE.pop(next(iter(_PK_DEVICE_CACHE)))
        buf = (jax.device_put(arr) if device is None
               else jax.device_put(arr, device))
        _PK_DEVICE_CACHE[key] = buf
        return buf


class PackScratch:
    """Per-worker reusable host packing buffers.

    The stream packer used to allocate (and page-fault) a fresh multi-MB
    preimage matrix per segment — a measurable slice of the pack share the
    bench gates (7% -> 11.1% r04->r05). Intermediates now reuse one
    per-thread buffer per dtype, re-zeroed in place (memset, no fault
    storm). ONLY intermediates: arrays handed across the device boundary
    are freshly allocated every call, because jax may alias aligned host
    buffers on the CPU backend and a reused buffer could be overwritten
    while a previous segment's transfer is still in flight."""

    __slots__ = ("_u8", "_u32")

    def __init__(self):
        self._u8 = None
        self._u32 = None

    def zeros_u8(self, shape) -> np.ndarray:
        n = int(np.prod(shape))
        if self._u8 is None or self._u8.size < n:
            self._u8 = np.zeros(max(n, 1), dtype=np.uint8)
        else:
            self._u8[:n] = 0
        return self._u8[:n].reshape(shape)

    def empty_u32(self, shape) -> np.ndarray:
        n = int(np.prod(shape))
        if self._u32 is None or self._u32.size < n:
            self._u32 = np.empty(max(n, 1), dtype=np.uint32)
        return self._u32[:n].reshape(shape)


_SCRATCH = threading.local()


def _thread_scratch() -> PackScratch:
    s = getattr(_SCRATCH, "scratch", None)
    if s is None:
        s = _SCRATCH.scratch = PackScratch()
    return s


def _sig_pk_arrays(pks, sigs):
    """Shared host plumbing of the dense and sparse packers: length checks,
    zero-substitution for malformed rows, the vectorized s < L compare.
    Returns (r_arr (n,32), s_arr (n,32), pk_arr (n,32), ok (n,))."""
    n = len(pks)
    pk_lens = np.array(list(map(len, pks)), dtype=np.int64)
    sig_lens = np.array(list(map(len, sigs)), dtype=np.int64)
    ok = (pk_lens == 32) & (sig_lens == 64)
    if ok.all():
        pk_l, sig_l = pks, sigs
    else:
        zpk, zsig = b"\x00" * 32, b"\x00" * 64
        pk_l = [pk if o else zpk for pk, o in zip(pks, ok)]
        sig_l = [sg if o else zsig for sg, o in zip(sigs, ok)]
    sig_arr = np.frombuffer(b"".join(sig_l), dtype=np.uint8).reshape(n, 64)
    r_arr = np.ascontiguousarray(sig_arr[:, :32])
    s_arr = np.ascontiguousarray(sig_arr[:, 32:])
    pk_arr = np.frombuffer(b"".join(pk_l), dtype=np.uint8).reshape(n, 32)
    ok &= _s_lt_l(s_arr)
    return r_arr, s_arr, pk_arr, ok


def _sparse_from_rows(msgs, chunk: int):
    """Discover the sparse structure of a row-materialized batch: join the
    rows into one matrix and diff-scan against per-chunk templates. Each
    scan chunk gets its own template (its first row): a fast-sync window
    concatenates several commits whose height/block_id bytes are constant
    WITHIN a commit but differ across them — per-chunk templates keep the
    diff-column union near the per-signature minimum.

    Returns (templates (k, MLEN) cols-zeroed, cols (C,), diff_vals (pad, C),
    mlens (n,), k, pad) or None when the rows are too dissimilar."""
    n = len(msgs)
    mlens = np.array(list(map(len, msgs)), dtype=np.int64)
    bucket = _nblk_bucket(int(mlens.max()))
    mlen_max = bucket * 128 - 64
    k = -(-n // chunk)
    pad = k * chunk
    arr = np.zeros((pad, mlen_max), dtype=np.uint8)
    if n and mlens.max() == mlens.min():
        ml = int(mlens[0])
        if ml:
            arr[:n, :ml] = np.frombuffer(
                b"".join(msgs), dtype=np.uint8).reshape(n, ml)
    else:
        flat_src = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(mlens[:-1], out=starts[1:])
        within = (np.arange(flat_src.shape[0], dtype=np.int64)
                  - np.repeat(starts, mlens))
        dst = np.repeat(np.arange(n, dtype=np.int64) * mlen_max, mlens) + within
        arr.reshape(-1)[dst] = flat_src
    templates = arr[::chunk].copy()                      # (k, MLEN)
    if pad > n:  # padded rows mirror their template: no diff contribution
        arr[n:] = templates[-1]
    tiled = np.repeat(templates, chunk, axis=0)          # (pad, MLEN)
    diff = (arr != tiled).any(axis=0)
    cols = np.nonzero(diff)[0].astype(np.int32)
    if cols.shape[0] > MAX_SPARSE_COLS:
        return None
    templates[:, cols] = 0  # diff columns are fully per-item
    # padding duplicates column 0 (same value rewritten — harmless)
    c_pad = _c_pad_bucket(cols.shape[0])
    if c_pad > cols.shape[0]:
        cols = np.concatenate(
            [cols, np.zeros(c_pad - cols.shape[0], np.int32)])
    diff_vals = np.ascontiguousarray(arr[:, cols])       # (pad, C)
    return templates, cols, diff_vals, mlens, k, pad


def _sparse_from_columns(columns, chunk: int):
    """The zero-copy fast path: the caller (a VerifyCommit* plane) already
    knows the batch's columnar structure (crypto/signcols.SignColumns from
    the canonical encoder), so the join + diff scan above is skipped
    entirely — templates and diff values are sliced straight from the
    columns object. Same return contract as :func:`_sparse_from_rows`."""
    n = len(columns)
    base_cols = columns.cols
    if base_cols.shape[0] > MAX_SPARSE_COLS:
        return None
    bucket = _nblk_bucket(columns.mlen)
    mlen_max = bucket * 128 - 64
    k = -(-n // chunk)
    pad = k * chunk
    template = np.zeros(mlen_max, dtype=np.uint8)
    template[:columns.mlen] = columns.template
    c = base_cols.shape[0]
    c_pad = _c_pad_bucket(c)
    # duplicated pad columns repeat the first diff column (or column 0 for
    # an all-identical batch) with the SAME value per row, so scatter write
    # order cannot matter
    pad_col = int(base_cols[0]) if c else 0
    cols = np.full(c_pad, pad_col, dtype=np.int32)
    cols[:c] = base_cols
    orig_at_cols = template[cols].copy()  # pre-zeroing template bytes
    diff_vals = np.empty((pad, c_pad), dtype=np.uint8)
    if c:
        diff_vals[:n, :c] = columns.vals
        diff_vals[:n, c:] = columns.vals[:, :1]
    else:
        diff_vals[:n] = orig_at_cols
    diff_vals[n:] = orig_at_cols  # padded rows mirror the template
    template[cols] = 0
    templates = np.repeat(template[None, :], k, axis=0)
    mlens = np.full(n, columns.mlen, dtype=np.int64)
    return templates, cols, diff_vals, mlens, k, pad


def prepare_sparse_stream(pks, msgs, sigs, chunk: int, columns=None,
                          device=None):
    """Pack a same-bucket batch into the sparse wire format, or return None
    when the messages are too dissimilar for it to pay.

    ``columns`` (crypto/signcols.SignColumns, aligned 1:1 with the batch)
    short-circuits structure discovery; ``device`` commits every input to
    an explicit device — the multi-device pool's per-lane placement.

    Returns (device_args tuple for _verify_sparse_stream_kernel, ok mask).
    """
    n = len(pks)
    built = None
    if columns is not None and len(columns) == n:
        built = _sparse_from_columns(columns, chunk)
    if built is None:
        built = _sparse_from_rows(msgs, chunk)
    if built is None:
        return None
    templates, cols, diff_vals, mlens, k, pad = built

    r_arr, s_arr, pk_arr, ok = _sig_pk_arrays(pks, sigs)
    if pad > n:
        r_arr = np.pad(r_arr, ((0, pad - n), (0, 0)))
        pk_arr = np.pad(pk_arr, ((0, pad - n), (0, 0)))
        s_arr = np.pad(s_arr, ((0, pad - n), (0, 0)))
        mlens = np.pad(mlens, (0, pad - n))
    b = chunk // LANE

    def to_chunks(a2d, width):  # (pad, W) -> (k, W, b, LANE)
        return np.ascontiguousarray(
            a2d.reshape(k, chunk, width).transpose(0, 2, 1)
        ).reshape(k, width, b, LANE)

    put = (jnp.asarray if device is None
           else (lambda x: jax.device_put(x, device)))
    args = (
        put(templates),
        put(cols),
        put(to_chunks(diff_vals, diff_vals.shape[1])),
        put(mlens.astype(np.int32).reshape(k, b, LANE)),
        put(to_chunks(r_arr, 32)),
        _device_cached(to_chunks(pk_arr, 32), device=device),
        put(to_chunks(s_arr, 32)),
    )
    return args, ok


def _s_lt_l(s_arr: np.ndarray) -> np.ndarray:
    """(n, 32) u8 LE scalars -> (n,) bool s < L (vectorized lexicographic)."""
    s64 = s_arr.view("<u8")
    n = s_arr.shape[0]
    lt = np.zeros(n, dtype=bool)
    decided = np.zeros(n, dtype=bool)
    for w in (3, 2, 1, 0):
        lw = _L_WORDS[w]
        lt |= ~decided & (s64[:, w] < lw)
        decided |= s64[:, w] != lw
    return lt


def _pad_to(n: int) -> int:
    """Bucket batch sizes to limit jit recompiles; multiple of 128 so the
    batch reshapes exactly to (B, 128) lanes."""
    size = LANE
    while size < n:
        size *= 2
    return size


def prepare_batch(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes],
    rows: Optional[int] = None, min_nblk: Optional[int] = None,
    scratch: Optional[PackScratch] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack (pk, msg, sig) tuples into kernel inputs + host validity mask.

    Returns (blocks (R, NBLK, 32) u32 BE, nblk (R,) i32, s_words (R, 8) u32,
    ok (N,) bool). All numpy, vectorized except cheap per-item length/bytes
    plumbing. ``rows`` (>= n) allocates padded zero rows up front and
    ``min_nblk`` widens the block axis to a caller-chosen bucket, so the
    stream packer no longer re-copies via np.pad; ``scratch`` routes the
    big intermediates through a reusable per-worker buffer (the outputs
    then ALIAS scratch memory — callers must consume them before the next
    scratch-using call on the same thread and never hand them to jax).
    """
    if not (len(pks) == len(msgs) == len(sigs)):
        raise ValueError(
            f"batch length mismatch: {len(pks)} pks, {len(msgs)} msgs, {len(sigs)} sigs"
        )
    n = len(pks)
    if n == 0:
        return (np.zeros((0, 1, 32), np.uint32), np.zeros(0, np.int32),
                np.zeros((0, 8), np.uint32), np.zeros(0, bool))
    out_rows = n if rows is None else rows
    r_arr, s_arr, pk_arr, ok = _sig_pk_arrays(pks, sigs)

    # SHA-512 preimage blocks: R || A || M || 0x80 pad || 128-bit BE bitlen
    mlens = np.array(list(map(len, msgs)), dtype=np.int64)
    nblk = ((64 + mlens + 17 + 127) // 128).astype(np.int32)
    nblk_max = int(nblk.max())
    if min_nblk is not None and min_nblk > nblk_max:
        nblk_max = min_nblk
    if scratch is not None:
        blocks = scratch.zeros_u8((out_rows, nblk_max * 128))
    else:
        blocks = np.zeros((out_rows, nblk_max * 128), dtype=np.uint8)
    blocks[:n, :32] = r_arr
    blocks[:n, 32:64] = pk_arr
    if n and mlens.max() == mlens.min():
        ml = int(mlens[0])
        if ml:
            blocks[:n, 64:64 + ml] = np.frombuffer(
                b"".join(msgs), dtype=np.uint8).reshape(n, ml)
    elif int(mlens.sum()):
        # vectorized ragged scatter: flat destination index for every
        # message byte, built from cumulative offsets
        flat_src = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(mlens[:-1], out=starts[1:])
        width = blocks.shape[1]
        within = np.arange(flat_src.shape[0], dtype=np.int64) - np.repeat(starts, mlens)
        dst = np.repeat(np.arange(n, dtype=np.int64) * width + 64, mlens) + within
        blocks.reshape(-1)[dst] = flat_src
    rows_idx = np.arange(n)
    blocks[rows_idx, 64 + mlens] = 0x80
    bitlen = ((64 + mlens) * 8).astype(np.uint64)
    last = nblk.astype(np.int64) * 128
    for k in range(8):
        blocks[rows_idx, last - 1 - k] = ((bitlen >> (8 * k)) & 0xFF).astype(np.uint8)

    # big-endian u32 view + native cast = one vectorized byteswap pass
    if scratch is not None:
        blocks_w = scratch.empty_u32((out_rows, nblk_max * 32))
        np.copyto(blocks_w, blocks.view(">u4"))
        blocks_w = blocks_w.reshape(out_rows, nblk_max, 32)
    else:
        blocks_w = blocks.view(">u4").astype(np.uint32).reshape(
            out_rows, nblk_max, 32)
    s_words = np.zeros((out_rows, 8), dtype=np.uint32)
    s_words[:n] = s_arr.view("<u4")
    if out_rows > n:
        nblk = np.concatenate([nblk, np.zeros(out_rows - n, np.int32)])
    return blocks_w, nblk, s_words, ok


def pack_device_inputs(blocks_w, nblk, s_words, pad: int):
    """(n, ...) numpy arrays -> padded device inputs shaped (.., B, 128).

    The 2-D batch layout puts 128 items on the lane axis and B = pad/128 on
    sublanes, so every per-limb (1, B, 128) slice occupies whole vregs.
    """
    n = blocks_w.shape[0]
    nblk_max = blocks_w.shape[1]
    if pad > n:
        blocks_w = np.pad(blocks_w, ((0, pad - n), (0, 0), (0, 0)))
        nblk = np.pad(nblk, (0, pad - n))
        s_words = np.pad(s_words, ((0, pad - n), (0, 0)))
    b = pad // LANE
    return (
        np.ascontiguousarray(blocks_w.transpose(1, 2, 0)).reshape(nblk_max, 32, b, LANE),
        nblk.reshape(b, LANE),
        np.ascontiguousarray(s_words.T).reshape(8, b, LANE),
    )


def _nblk_bucket(mlen: int) -> int:
    """Per-item padded SHA block count, rounded up to a power of two — the
    bucket key for grouping. Grouping bounds both memory (one long message
    must not inflate every row of the (n, NBLK*128) preimage buffer) and
    kernel recompiles (shapes quantize to power-of-two NBLK)."""
    nblk = (64 + mlen + 17 + 127) // 128
    b = 1
    while b < nblk:
        b *= 2
    return b


def _group_by_bucket(msgs: Sequence[bytes]):
    groups: dict = {}
    for i, m in enumerate(msgs):
        groups.setdefault(_nblk_bucket(len(m)), []).append(i)
    return groups


_DEV_LABEL = None


def _device_label() -> str:
    """Default device as a stable metric label ('cpu:0', 'tpu:0', ...)."""
    global _DEV_LABEL
    if _DEV_LABEL is None:
        try:
            d = jax.devices()[0]
            _DEV_LABEL = f"{d.platform}:{d.id}"
        except Exception:
            return "device"
    return _DEV_LABEL


def batch_verify(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    """(N,) bool — batched strict Ed25519 verification on the default device."""
    n = len(pks)
    if n == 0:
        return np.zeros(0, dtype=bool)
    groups = _group_by_bucket(msgs)
    if len(groups) > 1:
        out = np.zeros(n, dtype=bool)
        for idxs in groups.values():
            out[idxs] = batch_verify([pks[i] for i in idxs],
                                     [msgs[i] for i in idxs],
                                     [sigs[i] for i in idxs])
        return out
    rec = phases.Segment(sigs=n, chunk=_pad_to(n),
                         device=_device_label()).begin()
    blocks_w, nblk, s_words, ok = prepare_batch(pks, msgs, sigs)
    bucket = next(iter(groups))
    if blocks_w.shape[1] < bucket:  # pad NBLK up to the bucket size
        blocks_w = np.pad(blocks_w, ((0, 0), (0, bucket - blocks_w.shape[1]), (0, 0)))
    dev_in = pack_device_inputs(blocks_w, nblk, s_words, _pad_to(n))
    rec.pack_done()
    dev = _verify_kernel(*dev_in)
    rec.dispatched()
    try:
        t_w = time.perf_counter()
        verdict = np.asarray(dev).reshape(-1)[:n]
        rec.fetched(wait_s=time.perf_counter() - t_w)
    finally:
        rec.abandon()  # failed fetch must not wedge the in-flight gauge
    return verdict & ok


def _pack_stream_dense(pks, msgs, sigs, chunk: int):
    """Dense stream packing: (kernel args (K, ..) tuple, ok mask). Shared
    by _dispatch_stream's dense branch, the multi-device lanes, and
    tools/device_profile.py's per-device scale cells (which device_put the
    same arrays onto an explicit device).

    Intermediates ride the per-worker PackScratch (no fresh multi-MB
    allocation per segment); the three returned arrays are freshly
    allocated — they cross the device boundary, where jax may alias host
    memory."""
    n = len(pks)
    bucket = _nblk_bucket(max(map(len, msgs)))
    k = -(-n // chunk)
    pad = k * chunk
    blocks_w, nblk, s_words, ok = prepare_batch(
        pks, msgs, sigs, rows=pad, min_nblk=bucket,
        scratch=_thread_scratch())
    nblk_max = blocks_w.shape[1]
    b = chunk // LANE
    blocks_d = np.empty((k, nblk_max, 32, b, LANE), dtype=np.uint32)
    np.copyto(blocks_d.reshape(k, nblk_max, 32, chunk),
              blocks_w.reshape(k, chunk, nblk_max, 32).transpose(0, 2, 3, 1))
    nblk_d = nblk.reshape(k, b, LANE)
    s_d = np.empty((k, 8, b, LANE), dtype=np.uint32)
    np.copyto(s_d.reshape(k, 8, chunk),
              s_words.reshape(k, chunk, 8).transpose(0, 2, 1))
    return (blocks_d, nblk_d, s_d), ok


def _dispatch_stream(pks, msgs, sigs, chunk: int, device=None, columns=None):
    """Pack one whole-chunk segment and dispatch it (sparse path if the
    messages are template-compressible, dense otherwise). Returns
    (device_verdict, ok_mask) WITHOUT fetching — the caller decides when to
    block, which is what lets the pipeline overlap host packing and
    host->device transfer of segment i+1 with device compute of segment i.

    ``device`` commits the segment to an explicit device (a multi-device
    pool lane); ``columns`` is the caller's columnar sign-bytes structure
    (skips the sparse path's join + diff scan)."""
    sparse = prepare_sparse_stream(pks, msgs, sigs, chunk, columns=columns,
                                   device=device)
    if sparse is not None:
        args, ok = sparse
        phases.mark_pack_done()
        return _verify_sparse_stream_kernel(*args), ok
    args, ok = _pack_stream_dense(pks, msgs, sigs, chunk)
    phases.mark_pack_done()
    if device is not None:
        args = tuple(jax.device_put(a, device) for a in args)
    return _verify_stream_kernel(*args), ok


# Segmented pipelining: on remote-attached TPUs the relay serializes each
# dispatch's transfer+compute, but a SECOND thread's pack+dispatch overlaps
# with the first's in-flight execution (measured 913 ms -> 510 ms on the
# 61k-sig commit workload). Segments of SEG_CHUNKS scan-chunks bound both
# the per-dispatch payload and the number of distinct compiled K shapes.
SEG_CHUNKS = max(1, int(os.environ.get("TMTPU_SEG_CHUNKS", "10")))
# below this many signatures a single dispatch wins (and small CPU test
# batches never trigger fresh XLA compiles of segment-shaped kernels)
SEG_MIN_SIGS = int(os.environ.get("TMTPU_SEG_MIN_SIGS", "8192"))
_SEG_POOL = None
_SEG_POOL_LOCK = threading.Lock()


def _seg_pool():
    global _SEG_POOL
    with _SEG_POOL_LOCK:
        if _SEG_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _SEG_POOL = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="ed25519-seg")
        return _SEG_POOL


def _segment_sizes(k_total: int) -> list:
    """Split k_total scan-chunks into near-equal pipeline segments of at
    most SEG_CHUNKS each (near-equal keeps every pipeline stage busy; a
    [10, 1] tail split would leave the overlap window mostly empty). Two
    segments is the minimum for transfer/compute overlap; K values stay in
    {1..SEG_CHUNKS} so the set of compiled kernel shapes is bounded."""
    n_segs = max(2, -(-k_total // SEG_CHUNKS)) if k_total > 1 else 1
    base, extra = divmod(k_total, n_segs)
    return [base + (1 if i < extra else 0) for i in range(n_segs)]


def _run_dispatch(rec, pks, msgs, sigs, chunk: int, device=None,
                  columns=None):
    """One segment's pack + async dispatch with phase stamps, on whatever
    thread runs it (segment 0 / single-dispatch: the caller; pipeline
    segments: a worker; multi-device: the lane's worker). The
    active-segment slot lets _dispatch_stream close the pack phase from
    inside without changing its signature."""
    rec.begin()
    prev = phases.set_active(rec)
    try:
        # kwargs only when set: _dispatch_stream is a test seam whose
        # 4-positional-arg contract fakes rely on
        kw = {}
        if device is not None:
            kw["device"] = device
        if columns is not None:
            kw["columns"] = columns
        dev, ok = _dispatch_stream(pks, msgs, sigs, chunk, **kw)
    finally:
        phases.clear_active(prev)
    rec.dispatched()
    return dev, ok


def _verify_segmented(pks, msgs, sigs, chunk: int,
                      t_entry: float = None, columns=None) -> np.ndarray:
    n = len(pks)
    sizes = _segment_sizes(-(-n // chunk))
    col_of = ((lambda a, b: columns.slice(a, b)) if columns is not None
              else (lambda a, b: None))
    bounds, lo = [], 0
    for s in sizes:
        hi = min(lo + s * chunk, n)
        bounds.append((lo, hi))
        lo = hi
    # phase records: plane/height captured HERE (contextvars do not follow
    # work onto the pipeline workers), stamps filled on whichever thread
    # packs/dispatches, closed on this thread at fetch
    plane, height = phases.context()
    dev_label = _device_label()
    recs = [phases.Segment(sigs=b - a, chunk=chunk, seg=i,
                           n_segs=len(bounds), device=dev_label,
                           plane=plane, height=height)
            for i, (a, b) in enumerate(bounds)]
    if t_entry is not None:
        # charge the stream entry's host work (bucket grouping over every
        # message) to segment 0's pack phase: it is critical-path packing
        # cost, and leaving it unattributed would leave a hole in the
        # wall-clock accounting bench.py asserts over
        recs[0].t0 = t_entry
    pool = _seg_pool()
    # segment 0 packs+dispatches on the calling thread: on a cold jit cache
    # two workers would race to trace the same kernel shape (JAX does not
    # guarantee single-flight compilation across threads); dispatch is async
    # so the pipeline overlap is unaffected
    a0, b0 = bounds[0]
    futs = [_done_future(_run_dispatch(
        recs[0], pks[a0:b0], msgs[a0:b0], sigs[a0:b0], chunk,
        columns=col_of(a0, b0)))]
    futs += [
        pool.submit(_run_dispatch, recs[1], pks[a:b], msgs[a:b], sigs[a:b],
                    chunk, columns=col_of(a, b))
        for a, b in bounds[1:2]
    ]
    out = np.zeros(n, dtype=bool)
    try:
        for i, (a, b) in enumerate(bounds):
            t_wait0 = time.perf_counter()
            dev, ok = futs[i].result()
            if i + 2 < len(bounds):
                a2, b2 = bounds[i + 2]
                futs.append(pool.submit(
                    _run_dispatch, recs[i + 2], pks[a2:b2], msgs[a2:b2],
                    sigs[a2:b2], chunk, columns=col_of(a2, b2)))
            arr = np.asarray(dev)
            recs[i].fetched(wait_s=time.perf_counter() - t_wait0)
            out[a:b] = arr.reshape(-1)[:b - a] & ok
    finally:
        # an errored fetch (or a sibling segment's worker raising) must
        # drain the in-flight gauge for every already-dispatched segment
        for r in recs:
            r.abandon()
    phases.observe_overlap(recs)
    return out


def _done_future(value):
    from concurrent.futures import Future

    f = Future()
    f.set_result(value)
    return f


def _multidevice_pool():
    """The process's MultiDeviceStream pool, or None (single device, pool
    disabled via TMTPU_VERIFY_DEVICES, or the module failed to come up — a
    broken pool must never take down the single-device path)."""
    try:
        from . import multidevice

        return multidevice.pool()
    except Exception:
        return None


def batch_verify_stream(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes],
    chunk: int = 2048, columns=None,
) -> np.ndarray:
    """(N,) bool — verify a large batch as fixed-size chunks scanned inside
    as few device executions as possible: one per SEG_CHUNKS-chunk segment,
    double-buffered so segment i+1's host packing and transfer overlap
    segment i's device compute (amortizes per-dispatch overhead).

    Batches big enough to amortize per-device dispatch overhead shard
    round-robin across the multi-device pool (crypto/ed25519_jax/
    multidevice.py) when one is available — per-device packing workers,
    per-device circuit breakers, byte-identical verdicts either way.
    ``columns`` (crypto/signcols.SignColumns aligned 1:1 with the batch)
    lets VerifyCommit* callers hand the packer their sign-bytes structure
    instead of having it re-discovered per segment."""
    t_entry = time.perf_counter()
    n = len(pks)
    if n == 0:
        return np.zeros(0, dtype=bool)
    if chunk % LANE:
        raise ValueError(f"chunk must be a multiple of {LANE}")
    if columns is not None and len(columns) != n:
        columns = None
    if n <= chunk:
        return batch_verify(pks, msgs, sigs)
    groups = _group_by_bucket(msgs)
    if len(groups) > 1:  # see _nblk_bucket: memory + recompile bound
        out = np.zeros(n, dtype=bool)
        for idxs in groups.values():
            out[idxs] = batch_verify_stream([pks[i] for i in idxs],
                                            [msgs[i] for i in idxs],
                                            [sigs[i] for i in idxs], chunk)
        return out
    if n >= SEG_MIN_SIGS and n > chunk:
        md = _multidevice_pool()
        if md is not None and md.engaged(n):
            return md.verify(pks, msgs, sigs, chunk, columns=columns,
                             t_entry=t_entry)
        # the columns kwarg only when set: _verify_segmented is a test seam
        # whose positional contract fakes rely on
        if columns is not None:
            return _verify_segmented(pks, msgs, sigs, chunk,
                                     t_entry=t_entry, columns=columns)
        return _verify_segmented(pks, msgs, sigs, chunk, t_entry=t_entry)
    rec = phases.Segment(sigs=n, chunk=chunk, device=_device_label())
    rec.t0 = t_entry  # bucket grouping is critical-path pack cost
    dev, ok = _run_dispatch(rec, pks, msgs, sigs, chunk, columns=columns)
    try:
        t_w = time.perf_counter()
        arr = np.asarray(dev)
        rec.fetched(wait_s=time.perf_counter() - t_w)
    finally:
        rec.abandon()
    return arr.reshape(-1)[:n] & ok
