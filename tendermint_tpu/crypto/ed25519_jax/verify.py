"""Batched Ed25519 verification: host prep + one jitted TPU kernel call.

Split of work (SURVEY.md §7 "hard parts"):

* host (numpy/hashlib): length checks, s-canonicality (s < L), the SHA-512
  challenge hash h = H(R || A || M) mod L (sign-bytes are short; hashing is
  bandwidth-trivial and hashlib is C-speed), and limb/digit packing;
* device (jit): point decompression of A, [h](-A) via batched 4-bit windowed
  double-and-add, [s]B via a precomputed 64x16 niels table, the final
  encoding, and the byte-equality decision against R.

Accept/reject decisions are byte-identical to the host spec
(tendermint_tpu.crypto.ed25519.verify); differential tests enforce this on
valid, corrupted, and adversarial inputs.
"""

from __future__ import annotations

import hashlib
from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import curve
from . import field as F
from ..ed25519 import L


LANE = 128  # batch is reshaped to (B, 128) so per-limb ops fill (8,128) vregs


@partial(jax.jit, static_argnums=())
def _verify_kernel(a_y, a_sign, r_y, r_sign, s_digits, h_digits):
    A, ok_a = curve.decompress(a_y, a_sign)
    h_negA = curve.scalar_mul_windowed(curve.neg(A), h_digits)
    sB = curve.scalar_mul_base(s_digits)
    rprime = curve.add(sB, h_negA)
    y_enc, sign_enc = curve.encode(rprime)
    eq_r = jnp.all(y_enc == r_y, axis=0) & (sign_enc == r_sign)
    return ok_a & eq_r


def _nibbles(b: np.ndarray) -> np.ndarray:
    """(N, 32) le bytes -> (64, N) 4-bit window digits, LSB window first."""
    out = np.zeros((64, b.shape[0]), dtype=np.uint32)
    out[0::2] = (b & 0x0F).T
    out[1::2] = (b >> 4).T
    return out


def _pad_to(n: int) -> int:
    """Bucket batch sizes to limit jit recompiles; multiple of 128 so the
    batch reshapes exactly to (B, 128) lanes."""
    size = LANE
    while size < n:
        size *= 2
    return size


def prepare_batch(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> Tuple[np.ndarray, ...]:
    """Pack (pk, msg, sig) tuples into device-ready arrays + host validity mask."""
    if not (len(pks) == len(msgs) == len(sigs)):
        raise ValueError(
            f"batch length mismatch: {len(pks)} pks, {len(msgs)} msgs, {len(sigs)} sigs"
        )
    n = len(pks)
    ok = np.ones(n, dtype=bool)
    pk_arr = np.zeros((n, 32), dtype=np.uint8)
    r_arr = np.zeros((n, 32), dtype=np.uint8)
    s_arr = np.zeros((n, 32), dtype=np.uint8)
    h_arr = np.zeros((n, 32), dtype=np.uint8)
    for i, (pk, msg, sig) in enumerate(zip(pks, msgs, sigs)):
        if len(pk) != 32 or len(sig) != 64:
            ok[i] = False
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            ok[i] = False
            continue
        pk_arr[i] = np.frombuffer(pk, dtype=np.uint8)
        r_arr[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        s_arr[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        h = int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(), "little") % L
        h_arr[i] = np.frombuffer(h.to_bytes(32, "little"), dtype=np.uint8)
    return pk_arr, r_arr, s_arr, h_arr, ok


def pack_device_inputs(pk_arr, r_arr, s_arr, h_arr, pad: int):
    """numpy byte arrays -> padded device inputs shaped (.., B, 128).

    The 2-D batch layout puts 128 items on the lane axis and B = pad/128 on
    sublanes, so every per-limb (1, B, 128) slice occupies whole vregs.
    """
    n = pk_arr.shape[0]
    if pad > n:
        z = lambda a: np.pad(a, ((0, pad - n), (0, 0)))
        pk_arr, r_arr, s_arr, h_arr = z(pk_arr), z(r_arr), z(s_arr), z(h_arr)
    b = pad // LANE
    a_sign = (pk_arr[:, 31] >> 7).astype(np.uint32).reshape(b, LANE)
    r_sign = (r_arr[:, 31] >> 7).astype(np.uint32).reshape(b, LANE)
    pk_m = pk_arr.copy()
    pk_m[:, 31] &= 0x7F
    r_m = r_arr.copy()
    r_m[:, 31] &= 0x7F
    shape3 = (F.NLIMBS, b, LANE)
    return (
        F.bytes_to_limbs(pk_m).reshape(shape3),
        a_sign,
        F.bytes_to_limbs(r_m).reshape(shape3),
        r_sign,
        _nibbles(s_arr).reshape(64, b, LANE),
        _nibbles(h_arr).reshape(64, b, LANE),
    )


def batch_verify(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    """(N,) bool — batched strict Ed25519 verification on the default device."""
    n = len(pks)
    if n == 0:
        return np.zeros(0, dtype=bool)
    pk_arr, r_arr, s_arr, h_arr, ok = prepare_batch(pks, msgs, sigs)
    dev_in = pack_device_inputs(pk_arr, r_arr, s_arr, h_arr, _pad_to(n))
    verdict = np.asarray(_verify_kernel(*dev_in)).reshape(-1)[:n]
    return verdict & ok
