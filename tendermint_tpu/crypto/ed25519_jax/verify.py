"""Batched Ed25519 verification: vectorized host packing + on-device
SHA-512 / scalar reduction / curve arithmetic.

Split of work (SURVEY.md §7 "hard parts"):

* host (numpy, no per-item Python crypto): length checks, the s < L
  canonicality compare, and packing the SHA-512 preimage blocks
  (R || A || M, padded) plus the 32-byte s. R and A are recovered *from the
  first hash block* on device, so per-signature transfer is just the padded
  preimage + s + a block count (~300 B for vote-sized messages);
* device (one jitted call): SHA-512 of the preimage (sha512.py), reduction
  of the 512-bit challenge mod L and window-digit extraction (scalar.py),
  point decompression of A, [h](-A) via batched 4-bit windowed
  double-and-add, [s]B via a precomputed 64x16 niels table, and the final
  encoding/equality decision against R (curve.py).

Two entry points:

* :func:`batch_verify` — one kernel execution, for a single batch;
* :func:`batch_verify_stream` — a ``lax.scan`` over fixed-size chunks inside
  ONE execution. Dispatch of a jitted computation has a large fixed cost on
  remote-attached TPUs (~100 ms through a relay, measured), so sustained
  throughput requires amortizing it over many chunks per call.

Accept/reject decisions are byte-identical to the host spec
(tendermint_tpu.crypto.ed25519.verify, mirroring the reference's Go
x/crypto hot call at crypto/ed25519/ed25519.go:148-155); differential tests
enforce this on valid, corrupted, and adversarial inputs.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import curve
from . import field as F
from . import scalar as S
from . import sha512 as H
from ..ed25519 import L

LANE = 128  # batch is reshaped to (B, 128) so per-limb ops fill (8,128) vregs

# L as 4 little-endian u64 words, for the vectorized s < L compare
_L_WORDS = np.frombuffer(L.to_bytes(32, "little"), dtype="<u8").copy()


def _bswap32(x: jnp.ndarray) -> jnp.ndarray:
    return (x >> 24) | ((x >> 8) & 0xFF00) | ((x << 8) & 0xFF0000) | (x << 24)


def _le32_to_limbs15(words) -> jnp.ndarray:
    """8 (*batch,) u32 LE words (top bit already stripped) -> (17, *batch)."""
    out = []
    for k in range(F.NLIMBS):
        bit = F.RADIX * k
        w, off = bit // 32, bit % 32
        v = words[w] >> off
        if off > 32 - F.RADIX and w + 1 < 8:
            v = v | (words[w + 1] << (32 - off))
        out.append(v & F.MASK)
    return jnp.stack(out)


def _word_nibbles(words: jnp.ndarray) -> jnp.ndarray:
    """(8, *batch) u32 LE words -> (64, *batch) 4-bit digits, LSB first."""
    digs = []
    for nib in range(64):
        w, off = nib // 8, (nib % 8) * 4
        digs.append((words[w] >> off) & 15)
    return jnp.stack(digs)


@partial(jax.jit, static_argnums=())
def _verify_kernel(blocks, nblk, s_words):
    """blocks (NBLK, 32, *batch) u32 BE sha words of R||A||M padded;
    nblk (*batch,) i32; s_words (8, *batch) u32 LE. -> (*batch,) bool."""
    le0 = _bswap32(blocks[0])                    # bytes 0..127 as LE32 words
    r_words = [le0[i] for i in range(8)]
    a_words = [le0[8 + i] for i in range(8)]
    a_sign = a_words[7] >> 31
    r_sign = r_words[7] >> 31
    a_words[7] = a_words[7] & 0x7FFFFFFF
    r_words[7] = r_words[7] & 0x7FFFFFFF
    a_y = _le32_to_limbs15(a_words)
    r_y = _le32_to_limbs15(r_words)

    digest = H.sha512_blocks(blocks, nblk)
    h_digits = S.sc_reduce_digits(H.digest_le32(digest))
    s_digits = _word_nibbles(s_words)

    A, ok_a = curve.decompress(a_y, a_sign)
    # failed decompressions leave garbage coordinates that are not on the
    # curve, where the complete addition law's z != 0 guarantee (and hence
    # encode's batch-inversion precondition) does not hold — mask them to the
    # identity; their verdict is already forced false by ok_a.
    ident = curve.identity(a_y.shape[1:])
    A = curve.Point(*(jnp.where(ok_a[None], c, ic)
                      for c, ic in zip(A, ident)))
    h_negA = curve.scalar_mul_windowed(curve.neg(A), h_digits)
    sB = curve.scalar_mul_base(s_digits)
    rprime = curve.add(sB, h_negA)
    y_enc, sign_enc = curve.encode(rprime)
    eq_r = jnp.all(y_enc == r_y, axis=0) & (sign_enc == r_sign)
    return ok_a & eq_r


@partial(jax.jit, static_argnums=())
def _verify_stream_kernel(blocks, nblk, s_words):
    """Scan the verify kernel over K chunks in one execution.

    blocks (K, NBLK, 32, B, 128), nblk (K, B, 128), s_words (K, 8, B, 128).
    """
    def step(_, x):
        b, n, s = x
        return None, _verify_kernel.__wrapped__(b, n, s)

    _, out = jax.lax.scan(step, None, (blocks, nblk, s_words))
    return out


def _pad_to(n: int) -> int:
    """Bucket batch sizes to limit jit recompiles; multiple of 128 so the
    batch reshapes exactly to (B, 128) lanes."""
    size = LANE
    while size < n:
        size *= 2
    return size


def prepare_batch(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pack (pk, msg, sig) tuples into kernel inputs + host validity mask.

    Returns (blocks (N, NBLK, 32) u32 BE, nblk (N,) i32, s_words (N, 8) u32,
    ok (N,) bool). All numpy, vectorized except cheap per-item length/bytes
    plumbing.
    """
    if not (len(pks) == len(msgs) == len(sigs)):
        raise ValueError(
            f"batch length mismatch: {len(pks)} pks, {len(msgs)} msgs, {len(sigs)} sigs"
        )
    n = len(pks)
    if n == 0:
        return (np.zeros((0, 1, 32), np.uint32), np.zeros(0, np.int32),
                np.zeros((0, 8), np.uint32), np.zeros(0, bool))
    pk_lens = np.fromiter((len(p) for p in pks), dtype=np.int64, count=n)
    sig_lens = np.fromiter((len(s) for s in sigs), dtype=np.int64, count=n)
    ok = (pk_lens == 32) & (sig_lens == 64)
    if ok.all():
        pk_l, sig_l = pks, sigs
    else:
        zpk, zsig = b"\x00" * 32, b"\x00" * 64
        pk_l = [pk if o else zpk for pk, o in zip(pks, ok)]
        sig_l = [sg if o else zsig for sg, o in zip(sigs, ok)]
    sig_arr = np.frombuffer(b"".join(sig_l), dtype=np.uint8).reshape(n, 64)
    r_arr = sig_arr[:, :32]
    s_arr = np.ascontiguousarray(sig_arr[:, 32:])
    pk_arr = np.frombuffer(b"".join(pk_l), dtype=np.uint8).reshape(n, 32)

    # s < L, vectorized lexicographic compare on LE u64 words (most
    # significant word first)
    s64 = s_arr.view("<u8")                      # (n, 4)
    lt = np.zeros(n, dtype=bool)
    decided = np.zeros(n, dtype=bool)
    for w in (3, 2, 1, 0):
        lw = _L_WORDS[w]
        lt |= ~decided & (s64[:, w] < lw)
        decided |= s64[:, w] != lw
    ok &= lt

    # SHA-512 preimage blocks: R || A || M || 0x80 pad || 128-bit BE bitlen
    mlens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    nblk = ((64 + mlens + 17 + 127) // 128).astype(np.int32)
    nblk_max = int(nblk.max())
    blocks = np.zeros((n, nblk_max * 128), dtype=np.uint8)
    blocks[:, :32] = r_arr
    blocks[:, 32:64] = pk_arr
    if n and mlens.max() == mlens.min():
        ml = int(mlens[0])
        if ml:
            blocks[:, 64:64 + ml] = np.frombuffer(
                b"".join(msgs), dtype=np.uint8).reshape(n, ml)
    elif int(mlens.sum()):
        # vectorized ragged scatter: flat destination index for every
        # message byte, built from cumulative offsets
        flat_src = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(mlens[:-1], out=starts[1:])
        width = blocks.shape[1]
        within = np.arange(flat_src.shape[0], dtype=np.int64) - np.repeat(starts, mlens)
        dst = np.repeat(np.arange(n, dtype=np.int64) * width + 64, mlens) + within
        blocks.reshape(-1)[dst] = flat_src
    rows = np.arange(n)
    blocks[rows, 64 + mlens] = 0x80
    bitlen = ((64 + mlens) * 8).astype(np.uint64)
    last = nblk.astype(np.int64) * 128
    for k in range(8):
        blocks[rows, last - 1 - k] = ((bitlen >> (8 * k)) & 0xFF).astype(np.uint8)

    # big-endian u32 view + native cast = one vectorized byteswap pass
    blocks_w = blocks.view(">u4").astype(np.uint32).reshape(n, nblk_max, 32)
    s_words = np.ascontiguousarray(s_arr).view("<u4").astype(np.uint32)  # (n, 8)
    return blocks_w, nblk, s_words, ok


def pack_device_inputs(blocks_w, nblk, s_words, pad: int):
    """(n, ...) numpy arrays -> padded device inputs shaped (.., B, 128).

    The 2-D batch layout puts 128 items on the lane axis and B = pad/128 on
    sublanes, so every per-limb (1, B, 128) slice occupies whole vregs.
    """
    n = blocks_w.shape[0]
    nblk_max = blocks_w.shape[1]
    if pad > n:
        blocks_w = np.pad(blocks_w, ((0, pad - n), (0, 0), (0, 0)))
        nblk = np.pad(nblk, (0, pad - n))
        s_words = np.pad(s_words, ((0, pad - n), (0, 0)))
    b = pad // LANE
    return (
        np.ascontiguousarray(blocks_w.transpose(1, 2, 0)).reshape(nblk_max, 32, b, LANE),
        nblk.reshape(b, LANE),
        np.ascontiguousarray(s_words.T).reshape(8, b, LANE),
    )


def _nblk_bucket(mlen: int) -> int:
    """Per-item padded SHA block count, rounded up to a power of two — the
    bucket key for grouping. Grouping bounds both memory (one long message
    must not inflate every row of the (n, NBLK*128) preimage buffer) and
    kernel recompiles (shapes quantize to power-of-two NBLK)."""
    nblk = (64 + mlen + 17 + 127) // 128
    b = 1
    while b < nblk:
        b *= 2
    return b


def _group_by_bucket(msgs: Sequence[bytes]):
    groups: dict = {}
    for i, m in enumerate(msgs):
        groups.setdefault(_nblk_bucket(len(m)), []).append(i)
    return groups


def batch_verify(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    """(N,) bool — batched strict Ed25519 verification on the default device."""
    n = len(pks)
    if n == 0:
        return np.zeros(0, dtype=bool)
    groups = _group_by_bucket(msgs)
    if len(groups) > 1:
        out = np.zeros(n, dtype=bool)
        for idxs in groups.values():
            out[idxs] = batch_verify([pks[i] for i in idxs],
                                     [msgs[i] for i in idxs],
                                     [sigs[i] for i in idxs])
        return out
    blocks_w, nblk, s_words, ok = prepare_batch(pks, msgs, sigs)
    bucket = next(iter(groups))
    if blocks_w.shape[1] < bucket:  # pad NBLK up to the bucket size
        blocks_w = np.pad(blocks_w, ((0, 0), (0, bucket - blocks_w.shape[1]), (0, 0)))
    dev_in = pack_device_inputs(blocks_w, nblk, s_words, _pad_to(n))
    verdict = np.asarray(_verify_kernel(*dev_in)).reshape(-1)[:n]
    return verdict & ok


def batch_verify_stream(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes],
    chunk: int = 1024,
) -> np.ndarray:
    """(N,) bool — verify a large batch as K chunks scanned inside ONE
    device execution (amortizes per-dispatch overhead)."""
    n = len(pks)
    if n == 0:
        return np.zeros(0, dtype=bool)
    if chunk % LANE:
        raise ValueError(f"chunk must be a multiple of {LANE}")
    if n <= chunk:
        return batch_verify(pks, msgs, sigs)
    groups = _group_by_bucket(msgs)
    if len(groups) > 1:  # see _nblk_bucket: memory + recompile bound
        out = np.zeros(n, dtype=bool)
        for idxs in groups.values():
            out[idxs] = batch_verify_stream([pks[i] for i in idxs],
                                            [msgs[i] for i in idxs],
                                            [sigs[i] for i in idxs], chunk)
        return out
    blocks_w, nblk, s_words, ok = prepare_batch(pks, msgs, sigs)
    bucket = next(iter(groups))
    if blocks_w.shape[1] < bucket:
        blocks_w = np.pad(blocks_w, ((0, 0), (0, bucket - blocks_w.shape[1]), (0, 0)))
    k = -(-n // chunk)
    pad = k * chunk
    nblk_max = blocks_w.shape[1]
    if pad > n:
        blocks_w = np.pad(blocks_w, ((0, pad - n), (0, 0), (0, 0)))
        nblk = np.pad(nblk, (0, pad - n))
        s_words = np.pad(s_words, ((0, pad - n), (0, 0)))
    b = chunk // LANE
    blocks_d = np.ascontiguousarray(
        blocks_w.reshape(k, chunk, nblk_max, 32).transpose(0, 2, 3, 1)
    ).reshape(k, nblk_max, 32, b, LANE)
    nblk_d = nblk.reshape(k, b, LANE)
    s_d = np.ascontiguousarray(
        s_words.reshape(k, chunk, 8).transpose(0, 2, 1)
    ).reshape(k, 8, b, LANE)
    verdict = np.asarray(_verify_stream_kernel(blocks_d, nblk_d, s_d))
    return verdict.reshape(-1)[:n] & ok
