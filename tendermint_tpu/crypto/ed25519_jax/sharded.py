"""Multi-chip Ed25519 verification plane.

The (msg, sig, pk) batch — laid out ``(17, B, 128)`` limbs / ``(B, 128)``
flags — is sharded across a 1-D device mesh on the **batch (sublane) axis**
``B``, never the 128-lane axis: each per-device shard keeps whole
``(.., 128)`` lane tiles (full vregs), and mesh size is not capped by the
lane width. Each chip verifies its shard locally, then the tallied voting
power crosses the mesh with a single ``psum`` over ICI — the distributed
2/3-majority check that replaces the reference's per-node scalar tally loop
(reference types/vote_set.go:449, types/validator_set.go:667).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .verify import LANE, _pad_to, _verify_kernel, pack_device_inputs, prepare_batch

AXIS = "sig_batch"

LIMB_SPEC = P(None, AXIS, None)   # (17|64, B, 128): shard the B sublane axis
FLAG_SPEC = P(AXIS, None)         # (B, 128)


def make_mesh(n_devices: int) -> Mesh:
    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}; spawn a virtual "
            "CPU mesh (JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n_devices}) to dry-run multi-chip paths"
        )
    return Mesh(np.array(devices[:n_devices]), axis_names=(AXIS,))


def _sharded_step(mesh: Mesh):
    from jax.experimental.shard_map import shard_map

    def full_step(a_y, a_sign, r_y, r_sign, s_digits, h_digits, powers):
        verdict = _verify_kernel.__wrapped__(
            a_y, a_sign, r_y, r_sign, s_digits, h_digits)
        local_tally = jnp.sum(jnp.where(verdict, powers, 0))
        total = jax.lax.psum(local_tally, axis_name=AXIS)
        return verdict, total

    specs = dict(
        in_specs=(LIMB_SPEC, FLAG_SPEC, LIMB_SPEC, FLAG_SPEC,
                  LIMB_SPEC, LIMB_SPEC, FLAG_SPEC),
        out_specs=(FLAG_SPEC, P()),
    )
    try:  # replication checking chokes on scan carries that become varying
        sharded = shard_map(full_step, mesh=mesh, check_vma=False, **specs)
    except TypeError:  # older JAX spells it check_rep
        sharded = shard_map(full_step, mesh=mesh, check_rep=False, **specs)
    return jax.jit(sharded)


def batch_verify_sharded(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    powers: Optional[Sequence[int]] = None,
    mesh: Optional[Mesh] = None,
    n_devices: Optional[int] = None,
) -> Tuple[np.ndarray, int]:
    """Verify a batch over a device mesh; -> ((N,) bool verdicts, psum tally).

    The batch pads to a multiple of ``n_devices * 128`` so the sublane axis
    divides evenly across the mesh. The returned tally is the device-side
    psum of ``powers`` over accepted signatures (int32 — a demo of the
    collective; exact int64 accounting stays host-side in VoteSet).
    """
    if mesh is None:
        mesh = make_mesh(n_devices or len(jax.devices()))
    d = mesh.devices.size
    n = len(pks)
    pk_arr, r_arr, s_arr, h_arr, ok = prepare_batch(pks, msgs, sigs)
    pad = max(_pad_to(max(n, 1)), d * LANE)
    dev_in = pack_device_inputs(pk_arr, r_arr, s_arr, h_arr, pad)
    b = pad // LANE

    pw = np.zeros(pad, dtype=np.int32)
    if powers is not None:
        pw[:n] = np.asarray(list(powers), dtype=np.int32)
    else:
        pw[:n] = 1
    pw[:n] *= ok  # host-invalid entries contribute no power
    pw = pw.reshape(b, LANE)

    put = lambda x, spec: jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))
    args = (
        put(dev_in[0], LIMB_SPEC), put(dev_in[1], FLAG_SPEC),
        put(dev_in[2], LIMB_SPEC), put(dev_in[3], FLAG_SPEC),
        put(dev_in[4], LIMB_SPEC), put(dev_in[5], LIMB_SPEC),
        put(pw, FLAG_SPEC),
    )
    verdict, total = _sharded_step(mesh)(*args)
    verdict = np.asarray(verdict).reshape(-1)[:n] & ok
    return verdict, int(total)
