"""Multi-chip Ed25519 verification plane.

The packed signature batch — SHA preimage blocks ``(NBLK, 32, B, 128)``,
block counts ``(B, 128)``, s-words ``(8, B, 128)`` — is sharded across a
1-D device mesh on the **batch (sublane) axis** ``B``, never the 128-lane
axis: each per-device shard keeps whole ``(.., 128)`` lane tiles (full
vregs), and mesh size is not capped by the lane width. Each chip verifies
its shard locally, then the tallied voting power crosses the mesh with a
single ``psum`` over ICI — the distributed 2/3-majority check that replaces
the reference's per-node scalar tally loop (reference
types/vote_set.go:449, types/validator_set.go:667).

The tally is EXACT for int64 voting powers: each power is split host-side
into eight 8-bit limbs (2^64 covers MaxTotalVotingPower = 2^60), the
per-limb sums ride the psum as int32 (safe for up to 2^22 signatures
globally: 255 · 2^22 < 2^31 — commit scale, 10k+ validators, with 400x
headroom), and the host recombines ``Σ psum_j · 2^8j`` in Python ints.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import phases
from .verify import LANE, _pad_to, _verify_kernel, pack_device_inputs, prepare_batch

AXIS = "sig_batch"

BLOCK_SPEC = P(None, None, AXIS, None)  # (NBLK, 32, B, 128): shard sublanes
WORD_SPEC = P(None, AXIS, None)         # (8, B, 128)
FLAG_SPEC = P(AXIS, None)               # (B, 128)

POWER_LIMB_BITS = 8
POWER_LIMBS = 8                          # 8 x 8-bit limbs cover int64 powers
MAX_EXACT_SIGS = 1 << 22                 # int32-safe limb-sum bound (255·2^22 < 2^31)


def make_mesh(n_devices: int) -> Mesh:
    devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}; spawn a virtual "
            "CPU mesh (JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n_devices}) to dry-run multi-chip paths"
        )
    return Mesh(np.array(devices[:n_devices]), axis_names=(AXIS,))


# mesh identity -> jitted step: rebuilding shard_map + jax.jit per call
# created a FRESH wrapper whose trace cache was empty, so every repeated
# sharded call re-traced (and on a cold persistent cache re-compiled) the
# whole verify kernel. Keyed by device ids + axis names — two Mesh objects
# over the same devices share one compiled step.
_STEP_CACHE: dict = {}
_STEP_LOCK = threading.Lock()


def _sharded_step(mesh: Mesh):
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    with _STEP_LOCK:
        hit = _STEP_CACHE.get(key)
        if hit is not None:
            return hit

    try:
        from jax import shard_map
    except ImportError:  # older JAX
        from jax.experimental.shard_map import shard_map

    def full_step(blocks, nblk, s_words, power_limbs):
        verdict = _verify_kernel.__wrapped__(blocks, nblk, s_words)
        # (8, B, 128) int32 8-bit limb planes; zero out rejected signatures
        masked = jnp.where(verdict[None], power_limbs, 0)
        local = jnp.sum(masked, axis=(1, 2))          # (POWER_LIMBS,) int32
        total_limbs = jax.lax.psum(local, axis_name=AXIS)
        return verdict, total_limbs

    specs = dict(
        in_specs=(BLOCK_SPEC, FLAG_SPEC, WORD_SPEC, WORD_SPEC),
        out_specs=(FLAG_SPEC, P()),
    )
    try:  # replication checking chokes on scan carries that become varying
        sharded = shard_map(full_step, mesh=mesh, check_vma=False, **specs)
    except TypeError:  # older JAX spells it check_rep
        sharded = shard_map(full_step, mesh=mesh, check_rep=False, **specs)
    step = jax.jit(sharded)
    with _STEP_LOCK:
        # a racing builder may have landed first; keep the winner so every
        # caller shares one trace cache
        return _STEP_CACHE.setdefault(key, step)


def _power_limbs(powers: np.ndarray, pad: int, b: int) -> np.ndarray:
    """(n,) int64 -> (8, B, 128) int32 planes of 8-bit limbs."""
    out = np.zeros((POWER_LIMBS, pad), dtype=np.int32)
    p = powers.astype(np.uint64)
    for j in range(POWER_LIMBS):
        out[j, : len(powers)] = (
            (p >> (POWER_LIMB_BITS * j)) & ((1 << POWER_LIMB_BITS) - 1)
        ).astype(np.int32)
    return out.reshape(POWER_LIMBS, b, LANE)


def batch_verify_sharded(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    powers: Optional[Sequence[int]] = None,
    mesh: Optional[Mesh] = None,
    n_devices: Optional[int] = None,
) -> Tuple[np.ndarray, int]:
    """Verify a batch over a device mesh; -> ((N,) bool verdicts, exact tally).

    The batch pads to a multiple of ``n_devices * 128`` so the sublane axis
    divides evenly across the mesh. The returned tally is the exact int64
    sum of ``powers`` over accepted signatures, computed with a device-side
    psum of 8-bit limb planes (see module docstring).
    """
    if mesh is None:
        mesh = make_mesh(n_devices or len(jax.devices()))
    d = mesh.devices.size
    n = len(pks)
    if n > MAX_EXACT_SIGS:
        raise ValueError(
            f"batch of {n} exceeds the exact-tally bound {MAX_EXACT_SIGS}; "
            "split into multiple calls"
        )
    # phase record: one segment spread over the whole mesh; per-device
    # dispatch/in-flight series get every mesh device's label
    labels = [f"{dev.platform}:{dev.id}" for dev in mesh.devices.flat]
    rec = phases.Segment(sigs=n, chunk=0, device=f"mesh[{d}]",
                         devices=labels).begin()
    blocks_w, nblk, s_words, ok = prepare_batch(pks, msgs, sigs)
    # round up to a multiple of d*LANE so the B axis divides across the mesh
    unit = d * LANE
    pad = -(-max(_pad_to(max(n, 1)), unit) // unit) * unit
    dev_in = pack_device_inputs(blocks_w, nblk, s_words, pad)
    b = pad // LANE

    pw = np.zeros(n, dtype=np.int64)
    if powers is not None:
        pw[:] = np.asarray(list(powers), dtype=np.int64)
    else:
        pw[:] = 1
    pw *= ok  # host-invalid entries contribute no power
    limbs = _power_limbs(pw, pad, b)

    put = lambda x, spec: jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))
    args = (
        put(dev_in[0], BLOCK_SPEC), put(dev_in[1], FLAG_SPEC),
        put(dev_in[2], WORD_SPEC), put(limbs, WORD_SPEC),
    )
    rec.chunk = pad
    rec.pack_done()
    verdict_d, total_limbs = _sharded_step(mesh)(*args)
    rec.dispatched()
    try:
        t_w = time.perf_counter()
        verdict = np.asarray(verdict_d).reshape(-1)[:n] & ok
        tl = np.asarray(total_limbs)
        rec.fetched(wait_s=time.perf_counter() - t_w)
    finally:
        rec.abandon()  # failed fetch must not wedge the in-flight gauges
    total = sum(int(tl[j]) << (POWER_LIMB_BITS * j) for j in range(POWER_LIMBS))
    return verdict, total
