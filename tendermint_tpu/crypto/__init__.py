"""Pluggable key/signature interfaces (the seam from reference crypto/crypto.go:22-30).

`PubKey.verify_signature` is the scalar path; `BatchVerifier` (crypto/batch.py)
is the batched seam the reference lacks (SURVEY.md north star) — collect
(pk, msg, sig) tuples, verify all at once on TPU, fall back to scalar on CPU.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

from . import ed25519 as _ed

ADDRESS_SIZE = 20


def address_hash(b: bytes) -> bytes:
    """Address = first 20 bytes of SHA-256 (reference crypto/crypto.go:16)."""
    return hashlib.sha256(b).digest()[:ADDRESS_SIZE]


class PubKey:
    type_name: str = ""

    def address(self) -> bytes:
        raise NotImplementedError

    def bytes(self) -> bytes:
        raise NotImplementedError

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def __eq__(self, other):
        return isinstance(other, PubKey) and self.type_name == other.type_name \
            and self.bytes() == other.bytes()

    def __hash__(self):
        return hash((self.type_name, self.bytes()))


class PrivKey:
    type_name: str = ""

    def bytes(self) -> bytes:
        raise NotImplementedError

    def sign(self, msg: bytes) -> bytes:
        raise NotImplementedError

    def pub_key(self) -> PubKey:
        raise NotImplementedError


# --- ed25519 ---------------------------------------------------------------

ED25519_TYPE = "ed25519"

try:  # OpenSSL-backed fast scalar path, if present (it is in this image)
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey as _OSSLPub,
    )
    from cryptography.exceptions import InvalidSignature as _InvalidSig

    def _fast_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64 or len(pub) != 32:
            return False
        # OpenSSL accepts some encodings strict RFC-8032 rejects (non-canonical
        # A) and vice versa is not possible; re-check the cheap canonicality
        # rules here so decisions match ed25519.verify exactly.
        if int.from_bytes(sig[32:], "little") >= _ed.L:
            return False
        pub_int = int.from_bytes(pub, "little")
        y, x_sign = pub_int & ((1 << 255) - 1), pub_int >> 255
        if y >= _ed.P:
            return False
        # RFC 8032 §5.1.3: x=0 (y = ±1) with sign bit 1 is an invalid
        # encoding; OpenSSL accepts it, the strict spec and TPU path reject.
        if x_sign == 1 and y in (1, _ed.P - 1):
            return False
        try:
            _OSSLPub.from_public_bytes(pub).verify(sig, msg)
            return True
        except (_InvalidSig, ValueError):
            return False

    _HAVE_OSSL = True
except ImportError:  # pragma: no cover
    _fast_verify = None
    _HAVE_OSSL = False


@dataclass(frozen=True)
class Ed25519PubKey(PubKey):
    key: bytes
    type_name = ED25519_TYPE

    def address(self) -> bytes:
        return address_hash(self.key)

    def bytes(self) -> bytes:
        return self.key

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if _HAVE_OSSL:
            return _fast_verify(self.key, msg, sig)
        return _ed.verify(self.key, msg, sig)

    def __eq__(self, other):
        return PubKey.__eq__(self, other)

    def __hash__(self):
        return PubKey.__hash__(self)


@dataclass(frozen=True)
class Ed25519PrivKey(PrivKey):
    key: bytes  # 64 bytes: seed || pubkey
    type_name = ED25519_TYPE

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Ed25519PrivKey":
        priv, _ = _ed.keygen(seed)
        return Ed25519PrivKey(priv)

    def bytes(self) -> bytes:
        return self.key

    def sign(self, msg: bytes) -> bytes:
        return _ed.sign(self.key, msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self.key[32:])


# --- bls12-381 (min-sig: 96-byte G2 pubkeys, 48-byte G1 signatures) --------

BLS12381_TYPE = "bls12381"


@dataclass(frozen=True)
class Bls12381PubKey(PubKey):
    key: bytes  # compressed G2, 96 bytes
    type_name = BLS12381_TYPE

    def address(self) -> bytes:
        return address_hash(self.key)

    def bytes(self) -> bytes:
        return self.key

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        from . import bls12381 as _bls

        return _bls.verify(self.key, msg, sig)

    def __eq__(self, other):
        return PubKey.__eq__(self, other)

    def __hash__(self):
        return PubKey.__hash__(self)


def _bls_pubkey_bytes(sk_bytes: bytes) -> bytes:
    from . import bls12381 as _bls

    # keyed on a digest so the module-global memo never retains raw
    # secret-key bytes, and bounded so it cannot grow with key churn
    memo_key = hashlib.sha256(b"tmtpu-bls-pk-memo" + sk_bytes).digest()
    cached = _bls_pubkey_bytes._memo.get(memo_key)
    if cached is None:  # one G2 scalar mul (~15 ms) — memoize per secret
        if len(_bls_pubkey_bytes._memo) >= _BLS_PK_MEMO_MAX:
            _bls_pubkey_bytes._memo.clear()
        cached = _bls.sk_to_pk(_bls.sk_from_bytes(sk_bytes))
        _bls_pubkey_bytes._memo[memo_key] = cached
    return cached


_bls_pubkey_bytes._memo = {}
_BLS_PK_MEMO_MAX = 256


@dataclass(frozen=True)
class Bls12381PrivKey(PrivKey):
    key: bytes  # scalar mod r, 32 bytes big-endian
    type_name = BLS12381_TYPE

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Bls12381PrivKey":
        import os

        from . import bls12381 as _bls

        sk = _bls.sk_from_seed(seed if seed is not None else os.urandom(32))
        return Bls12381PrivKey(_bls.sk_to_bytes(sk))

    def bytes(self) -> bytes:
        return self.key

    def sign(self, msg: bytes) -> bytes:
        from . import bls12381 as _bls

        return _bls.sign(_bls.sk_from_bytes(self.key), msg)

    def pub_key(self) -> Bls12381PubKey:
        return Bls12381PubKey(_bls_pubkey_bytes(self.key))

    def pop(self) -> bytes:
        """Proof of possession — required to register the pubkey for
        aggregation (see crypto/bls12381 rogue-key notes)."""
        from . import bls12381 as _bls

        return _bls.pop_prove(_bls.sk_from_bytes(self.key))


def pubkey_from_type_and_bytes(type_name: str, b: bytes) -> PubKey:
    if type_name == ED25519_TYPE:
        if len(b) != 32:
            raise ValueError(f"ed25519 pubkey must be 32 bytes, got {len(b)}")
        return Ed25519PubKey(b)
    if type_name == BLS12381_TYPE:
        if len(b) != 96:
            raise ValueError(f"bls12381 pubkey must be 96 bytes, got {len(b)}")
        return Bls12381PubKey(b)
    if type_name == "secp256k1":
        from .secp256k1 import Secp256k1PubKey

        return Secp256k1PubKey(b)
    if type_name == "sr25519":
        from .sr25519 import Sr25519PubKey

        if len(b) != 32:
            raise ValueError(f"sr25519 pubkey must be 32 bytes, got {len(b)}")
        return Sr25519PubKey(b)
    raise ValueError(f"unknown pubkey type {type_name!r}")
