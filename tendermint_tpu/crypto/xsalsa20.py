"""XSalsa20-Poly1305 secretbox + legacy symmetric encryption
(reference crypto/xsalsa20symmetric/symmetric.go over nacl/secretbox).

Pure Python: these functions protect legacy ASCII-armored key files — a few
hundred bytes decrypted at CLI time — so clarity beats speed. Layout is
NaCl's exactly: ``encrypt_symmetric`` output is nonce(24) || tag(16) ||
cipher, with secret = SHA-256-shaped 32 bytes (the reference documents
"Sha256(Bcrypt(passphrase))"; see kdf()).

Primitives from their specs:
* Salsa20 core & stream (Bernstein, salsa20-ref.c semantics);
* HSalsa20 for the XSalsa20 nonce extension (NaCl paper, §10);
* Poly1305 over 2^130 - 5 (pinned to the RFC 8439 §2.5.2 vector);
* secretbox_seal/open pinned to the canonical NaCl test vector.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Optional

NONCE_LEN = 24
SECRET_LEN = 32
OVERHEAD = 16  # poly1305 tag

_SIGMA = b"expand 32-byte k"


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarterround(y0, y1, y2, y3):
    y1 ^= _rotl((y0 + y3) & 0xFFFFFFFF, 7)
    y2 ^= _rotl((y1 + y0) & 0xFFFFFFFF, 9)
    y3 ^= _rotl((y2 + y1) & 0xFFFFFFFF, 13)
    y0 ^= _rotl((y3 + y2) & 0xFFFFFFFF, 18)
    return y0, y1, y2, y3


def _doubleround(x):
    # columnround
    x[0], x[4], x[8], x[12] = _quarterround(x[0], x[4], x[8], x[12])
    x[5], x[9], x[13], x[1] = _quarterround(x[5], x[9], x[13], x[1])
    x[10], x[14], x[2], x[6] = _quarterround(x[10], x[14], x[2], x[6])
    x[15], x[3], x[7], x[11] = _quarterround(x[15], x[3], x[7], x[11])
    # rowround
    x[0], x[1], x[2], x[3] = _quarterround(x[0], x[1], x[2], x[3])
    x[5], x[6], x[7], x[4] = _quarterround(x[5], x[6], x[7], x[4])
    x[10], x[11], x[8], x[9] = _quarterround(x[10], x[11], x[8], x[9])
    x[15], x[12], x[13], x[14] = _quarterround(x[15], x[12], x[13], x[14])


def _core_words(key: bytes, inp: bytes):
    """Salsa20 state words for key(32) and input(16): the 4x4 matrix with
    the sigma constant on the diagonal."""
    k = struct.unpack("<8I", key)
    n = struct.unpack("<4I", inp)
    c = struct.unpack("<4I", _SIGMA)
    return [c[0], k[0], k[1], k[2],
            k[3], c[1], n[0], n[1],
            n[2], n[3], c[2], k[4],
            k[5], k[6], k[7], c[3]]


def salsa20_block(key: bytes, inp: bytes) -> bytes:
    """Salsa20 hash: 20 rounds + feed-forward (the stream block)."""
    x0 = _core_words(key, inp)
    x = list(x0)
    for _ in range(10):
        _doubleround(x)
    return struct.pack("<16I", *((a + b) & 0xFFFFFFFF
                                 for a, b in zip(x, x0)))


def hsalsa20(key: bytes, inp: bytes) -> bytes:
    """HSalsa20: 20 rounds, NO feed-forward; output words 0,5,10,15,6,7,8,9
    (NaCl paper — the XSalsa20 subkey derivation)."""
    x = _core_words(key, inp)
    for _ in range(10):
        _doubleround(x)
    return struct.pack("<8I", x[0], x[5], x[10], x[15],
                       x[6], x[7], x[8], x[9])


def salsa20_stream(key: bytes, nonce8: bytes, length: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < length:
        inp = nonce8 + struct.pack("<Q", counter)
        out += salsa20_block(key, inp)
        counter += 1
    return bytes(out[:length])


def poly1305(key32: bytes, msg: bytes) -> bytes:
    """RFC 8439 §2.5 one-time MAC over 2^130 - 5."""
    r = int.from_bytes(key32[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        n = int.from_bytes(blk + b"\x01", "little")
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _xsalsa20_key_nonce(key: bytes, nonce: bytes):
    subkey = hsalsa20(key, nonce[:16])
    return subkey, nonce[16:24]


def secretbox_seal(msg: bytes, nonce: bytes, key: bytes) -> bytes:
    """NaCl crypto_secretbox: returns tag(16) || cipher."""
    if len(key) != SECRET_LEN or len(nonce) != NONCE_LEN:
        raise ValueError("secretbox needs 32-byte key, 24-byte nonce")
    subkey, n8 = _xsalsa20_key_nonce(key, nonce)
    stream = salsa20_stream(subkey, n8, 32 + len(msg))
    cipher = bytes(a ^ b for a, b in zip(msg, stream[32:]))
    tag = poly1305(stream[:32], cipher)
    return tag + cipher


def secretbox_open(boxed: bytes, nonce: bytes, key: bytes) -> Optional[bytes]:
    """-> plaintext, or None on authentication failure."""
    if len(key) != SECRET_LEN or len(nonce) != NONCE_LEN:
        raise ValueError("secretbox needs 32-byte key, 24-byte nonce")
    if len(boxed) < OVERHEAD:
        return None
    tag, cipher = boxed[:OVERHEAD], boxed[OVERHEAD:]
    subkey, n8 = _xsalsa20_key_nonce(key, nonce)
    stream = salsa20_stream(subkey, n8, 32 + len(cipher))
    want = poly1305(stream[:32], cipher)
    # constant-time-ish compare (hmac.compare_digest semantics)
    import hmac

    if not hmac.compare_digest(tag, want):
        return None
    return bytes(a ^ b for a, b in zip(cipher, stream[32:]))


# -- the reference's symmetric seam (symmetric.go:19,36) ---------------------

def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """nonce(24) || secretbox(tag+cipher); secret must be 32 bytes."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be 32 bytes long, got {len(secret)}")
    nonce = os.urandom(NONCE_LEN)
    return nonce + secretbox_seal(plaintext, nonce, secret)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be 32 bytes long, got {len(secret)}")
    if len(ciphertext) <= OVERHEAD + NONCE_LEN:
        raise ValueError("ciphertext is too short")
    out = secretbox_open(ciphertext[NONCE_LEN:], ciphertext[:NONCE_LEN],
                         secret)
    if out is None:
        raise ValueError("ciphertext decryption failed")
    return out


def kdf(passphrase: str, salt: bytes = b"") -> bytes:
    """Passphrase -> 32-byte secret. The reference documents
    "Sha256(Bcrypt(passphrase))" (symmetric.go:17); bcrypt is unavailable
    in this image, so the work factor comes from PBKDF2-HMAC-SHA256 with a
    cost comparable to bcrypt(12). Key files record which KDF produced
    them, so formats stay self-describing."""
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt,
                               200_000, dklen=32)
