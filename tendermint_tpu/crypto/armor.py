"""OpenPGP-style ASCII armor (reference crypto/armor/armor.go over
golang.org/x/crypto/openpgp/armor) + encrypted key-file helpers.

RFC 4880 §6 framing: ``-----BEGIN <type>-----``, ``Key: Value`` headers, a
blank line, base64 body wrapped at 64 columns, a ``=XXXX`` CRC24 checksum
line, ``-----END <type>-----``. Byte-compatible with the Go encoder (same
wrap width, same radix-64 CRC24 with init 0xB704CE / poly 0x1864CFB).

The key-file helpers mirror the classic armored-privkey flow the reference
ecosystem uses on top of EncodeArmor: xsalsa20-poly1305 secretbox under a
passphrase-derived secret, KDF parameters recorded in the armor headers so
files remain self-describing.
"""

from __future__ import annotations

import base64
import os
from typing import Dict, Tuple

from . import xsalsa20

_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: Dict[str, str],
                 data: bytes) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    for i in range(0, len(b64), 64):
        lines.append(b64[i:i + 64])
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> Tuple[str, Dict[str, str], bytes]:
    """-> (block_type, headers, data); raises ValueError on bad framing or
    checksum (armor.go DecodeArmor surfaces the same failures)."""
    lines = [ln.rstrip("\r") for ln in armor_str.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN ") \
            or not lines[0].endswith("-----"):
        raise ValueError("invalid armor: missing BEGIN line")
    block_type = lines[0][len("-----BEGIN "):-len("-----")]
    end = f"-----END {block_type}-----"
    if lines[-1] != end:
        raise ValueError("invalid armor: missing END line")
    headers: Dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i]:
        if ":" not in lines[i]:
            break  # start of body without a blank separator (lenient)
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i]:
        i += 1
    body_lines = []
    crc_line = None
    for ln in lines[i:-1]:
        if ln.startswith("="):
            crc_line = ln[1:]
        else:
            body_lines.append(ln)
    try:
        data = base64.b64decode("".join(body_lines), validate=True)
    except Exception as e:
        raise ValueError(f"invalid armor body: {e}") from None
    if crc_line is not None:
        want = base64.b64decode(crc_line)
        if _crc24(data).to_bytes(3, "big") != want:
            raise ValueError("invalid armor: CRC24 checksum mismatch")
    return block_type, headers, data


# -- encrypted key files -----------------------------------------------------

BLOCK_PRIVKEY = "TENDERMINT PRIVATE KEY"


def encrypt_armor_priv_key(priv_bytes: bytes, passphrase: str,
                           key_type: str = "ed25519") -> str:
    salt = os.urandom(16)
    secret = xsalsa20.kdf(passphrase, salt)
    boxed = xsalsa20.encrypt_symmetric(priv_bytes, secret)
    return encode_armor(BLOCK_PRIVKEY, {
        "kdf": "pbkdf2-sha256-200000",
        "salt": salt.hex().upper(),
        "type": key_type,
    }, boxed)


def unarmor_decrypt_priv_key(armor_str: str,
                             passphrase: str) -> Tuple[bytes, str]:
    """-> (priv_bytes, key_type); ValueError on wrong passphrase/format."""
    block_type, headers, boxed = decode_armor(armor_str)
    if block_type != BLOCK_PRIVKEY:
        raise ValueError(f"unrecognized armor type {block_type!r}")
    if headers.get("kdf") != "pbkdf2-sha256-200000":
        raise ValueError(f"unrecognized KDF {headers.get('kdf')!r}")
    salt = bytes.fromhex(headers.get("salt", ""))
    secret = xsalsa20.kdf(passphrase, salt)
    try:
        priv = xsalsa20.decrypt_symmetric(boxed, secret)
    except ValueError:
        raise ValueError("invalid passphrase") from None
    return priv, headers.get("type", "")
