"""Device circuit breaker for the verification plane.

The device backend (TPU kernel, possibly behind a remote relay) can fail
persistently: a broken relay, a driver wedge, an XLA compile that never
lands. Before this breaker, every batch re-discovered the failure — paying
the dispatch timeout or exception each time — because the fallback had no
memory. Classic breaker state machine (Nygard, "Release It!"):

* CLOSED     — device route allowed; N consecutive failures trip it OPEN.
* OPEN       — zero device attempts; every batch routes straight to the
               host scalar path until ``cooldown_s`` elapses.
* HALF_OPEN  — after the cooldown, exactly ONE in-flight probe batch is
               allowed onto the device; success closes the breaker, failure
               re-opens it for another cooldown.

Shared by ``crypto/batch.py`` (BatchVerifier) and
``crypto/vote_batcher.py`` (the vote micro-batcher) through the module
singleton ``device_breaker`` — a relay failure seen by one caller protects
the other. Thread-safe: BatchVerifier runs on the apply-plane worker
thread, the vote batcher on executor threads.

Tuning: ``TMTPU_BREAKER_THRESHOLD`` (consecutive failures to trip,
default 3), ``TMTPU_BREAKER_COOLDOWN_S`` (seconds OPEN before a probe,
default 30). State + transitions export via CryptoMetrics when the node
wires ``set_breaker_metrics``.

Per-device lanes: the multi-device dispatcher
(``crypto/ed25519_jax/multidevice.py``) keeps one breaker PER DEVICE via
:func:`lane_breaker` (names ``device:<platform>:<id>``) so one sick chip
degrades the pool to N-1 healthy lanes instead of collapsing the whole
verification plane to host fallback. Lane knobs:
``TMTPU_DEVICE_BREAKER_THRESHOLD`` / ``TMTPU_DEVICE_BREAKER_COOLDOWN_S``
(falling back to the shared knobs above). Only when EVERY lane is sick
does the failure surface to the caller — and then the shared
``device_breaker`` takes over exactly as before.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
import weakref
from typing import Callable, Optional

logger = logging.getLogger("tmtpu.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: gauge encoding (README metric catalog): 0 closed, 1 open, 2 half-open
STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

DEFAULT_FAILURE_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 30.0

# CryptoMetrics hook (breaker_state / breaker_transitions_total), wired by
# the node alongside crypto.batch.set_crypto_metrics
metrics = None

# weak: tests construct many short-lived breakers; only live ones should
# re-export gauge state when metrics are wired
_BREAKERS: "weakref.WeakSet" = weakref.WeakSet()


def set_breaker_metrics(m) -> None:
    global metrics
    metrics = m
    if m is not None:
        for b in _BREAKERS:
            b._export_state(m)


class CircuitBreaker:
    def __init__(self, name: str = "device",
                 failure_threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        env_thr = os.environ.get("TMTPU_BREAKER_THRESHOLD")
        env_cd = os.environ.get("TMTPU_BREAKER_COOLDOWN_S")
        self.name = name
        self.failure_threshold = (failure_threshold if failure_threshold
                                  is not None else
                                  int(env_thr) if env_thr
                                  else DEFAULT_FAILURE_THRESHOLD)
        self.cooldown_s = (cooldown_s if cooldown_s is not None else
                           float(env_cd) if env_cd else DEFAULT_COOLDOWN_S)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started_at = 0.0
        self.stats = collections.Counter()
        _BREAKERS.add(self)

    # -- the routing seam ---------------------------------------------------

    def allow(self) -> bool:
        """May the caller attempt the device route right now? OPEN answers
        False (host path, no device attempt); an elapsed cooldown admits
        exactly one probe (HALF_OPEN) until its verdict arrives."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    self.stats["rejections"] += 1
                    return False
                self._transition(HALF_OPEN)
                self._probe_in_flight = True
                self._probe_started_at = self._clock()
                self.stats["probes"] += 1
                return True
            # HALF_OPEN: one probe at a time — but a probe whose verdict
            # never arrives (task cancelled mid-await, relay wedged) must
            # not latch the breaker shut forever; after a cooldown's worth
            # of silence the probe is presumed abandoned and a new one is
            # admitted
            if (self._probe_in_flight
                    and self._clock() - self._probe_started_at
                    < self.cooldown_s):
                self.stats["rejections"] += 1
                return False
            self._probe_in_flight = True
            self._probe_started_at = self._clock()
            self.stats["probes"] += 1
            return True

    def peek(self) -> bool:
        """Read-only: would :meth:`allow` admit a call right now? Unlike
        ``allow`` this neither admits a half-open probe nor counts a
        rejection — the multi-device planner uses it to pick healthy lanes
        without consuming probe slots on lanes it may not dispatch to."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return self._clock() - self._opened_at >= self.cooldown_s
            return not (self._probe_in_flight
                        and self._clock() - self._probe_started_at
                        < self.cooldown_s)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            self.stats["failures"] += 1
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN for another cooldown
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._opened_at = self._clock()
                self._transition(OPEN)

    # -- internals ----------------------------------------------------------

    def _transition(self, new: str) -> None:
        # caller holds the lock
        old, self._state = self._state, new
        self.stats[f"to_{new}"] += 1
        if new == OPEN:
            logger.warning(
                "circuit breaker %r OPEN after %d consecutive device "
                "failures; host path only for %.1fs", self.name,
                self._consecutive_failures, self.cooldown_s)
        else:
            logger.info("circuit breaker %r: %s -> %s", self.name, old, new)
        m = metrics
        if m is not None:
            m.breaker_transitions_total.labels(self.name, old, new).inc()
            m.breaker_state.labels(self.name).set(STATE_CODE[new])

    def _export_state(self, m) -> None:
        m.breaker_state.labels(self.name).set(STATE_CODE[self._state])

    # -- introspection / tests ---------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def reset(self) -> None:
        with self._lock:
            changed = self._state != CLOSED
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self.stats.clear()
        m = metrics
        if m is not None and changed:
            m.breaker_state.labels(self.name).set(STATE_CODE[CLOSED])


#: the shared device-route breaker (BatchVerifier + vote micro-batcher)
device_breaker = CircuitBreaker("device")


# -- per-device lane breakers -------------------------------------------------

#: device label ("tpu:3", "cpu:0") -> lane CircuitBreaker. Keyed by label,
#: not device object: a rebuilt pool after reset_pool() reuses the same
#: breaker state for the same physical chip.
_LANE_BREAKERS: dict = {}
_LANE_LOCK = threading.Lock()


def lane_breaker(label: str) -> CircuitBreaker:
    """The per-device breaker for one dispatch lane, created on first use.
    Lane knobs (``TMTPU_DEVICE_BREAKER_THRESHOLD`` /
    ``TMTPU_DEVICE_BREAKER_COOLDOWN_S``) are read at creation and fall back
    to the shared breaker defaults."""
    with _LANE_LOCK:
        b = _LANE_BREAKERS.get(label)
        if b is None:
            thr = os.environ.get("TMTPU_DEVICE_BREAKER_THRESHOLD")
            cd = os.environ.get("TMTPU_DEVICE_BREAKER_COOLDOWN_S")
            b = CircuitBreaker(
                f"device:{label}",
                failure_threshold=int(thr) if thr else None,
                cooldown_s=float(cd) if cd else None)
            _LANE_BREAKERS[label] = b
        return b


def lane_breakers() -> dict:
    """Snapshot of the live lane breakers (label -> CircuitBreaker)."""
    with _LANE_LOCK:
        return dict(_LANE_BREAKERS)


def reset_lane_breakers() -> None:
    """Reset every lane breaker and drop the registry (test fixtures; a
    later lane_breaker() re-reads the env knobs)."""
    with _LANE_LOCK:
        for b in _LANE_BREAKERS.values():
            b.reset()
        _LANE_BREAKERS.clear()


def classify_device_error(e: BaseException) -> str:
    """reason label for device_fallbacks_total: injected / compile_error /
    runtime_error (the cardinality-bounded taxonomy, not str(e))."""
    from ..libs.faults import InjectedFault

    if isinstance(e, InjectedFault):
        return "injected"
    name = type(e).__name__
    text = f"{name}: {e}".lower()
    if "compil" in text or name in ("XlaCompilationError",):
        return "compile_error"
    return "runtime_error"
