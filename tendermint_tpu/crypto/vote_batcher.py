"""Micro-batched verification for the streaming vote path (HOT LOOP #1).

The reference's hottest call site is one scalar ed25519 verify per gossiped
vote (types/vote_set.go:205 → vote.go:147). Votes arrive concurrently from
many peer tasks but are *consumed* by the single-writer consensus loop —
verifying inside that loop serializes everything, so batching must happen
in front of it:

* per-peer reactor tasks call :meth:`preverify` BEFORE enqueueing the vote
  to the state machine. Pre-verifications accumulate across peers; a flush
  fires when ``max_batch`` is reached or ``deadline_s`` after the first
  pending item (SURVEY.md §7: deadline micro-batching with host fallback);
* a flush below ``min_device_batch`` verifies on the host scalar path (a
  device call would cost more than it saves at low rate); above it, ONE
  batched device call covers every pending vote;
* verdicts land in a one-shot cache keyed by (pubkey, msg, sig). When the
  single-writer loop later reaches ``VoteSet.add_vote`` →
  :meth:`verify_vote`, the lookup hits and no signature work happens on the
  hot loop at all. A miss (catchup votes, adversarial replays, no reactor)
  falls back to the host scalar verify — correctness NEVER depends on
  pre-verification, and accept/reject stays byte-identical to the spec.

``stats`` counts device/host/cache traffic so tests can assert the device
path is provably taken.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..libs.faults import faults
from ..libs.trace import tracer
from . import batch as _batch  # module ref: reads the live metrics hook
from . import phases as _phases
from .breaker import classify_device_error, device_breaker

logger = logging.getLogger("tmtpu.votebatch")

# at/above this many pending sigs a flush goes to the device; below, host
DEFAULT_MIN_DEVICE_BATCH = 16
DEFAULT_MAX_BATCH = 1024
DEFAULT_DEADLINE_S = 0.003
# consensus liveness bound: if a device flush hasn't produced verdicts in
# this long (cold XLA compile on a fresh node, relay stall), the batch is
# re-verified on the host scalar path and later flushes stay host-side
# until the device call finally completes. Found in the wild: a catchup
# vote burst on a fresh node dispatched a cold-compile flush and consensus
# sat at the same height forever awaiting the verdict futures.
DEFAULT_DEVICE_TIMEOUT_S = 3.0
_CACHE_CAP = 16384


class BatchVoteVerifier:
    """Shared by the consensus reactor (preverify) and VoteSet (verify)."""

    def __init__(self, min_device_batch: int = DEFAULT_MIN_DEVICE_BATCH,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 deadline_s: float = DEFAULT_DEADLINE_S,
                 device_timeout_s: float = DEFAULT_DEVICE_TIMEOUT_S):
        self.min_device_batch = min_device_batch
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        self.device_timeout_s = device_timeout_s
        self._device_warming = False  # a device flush is past its deadline
        self._pending: List[Tuple[bytes, bytes, bytes, bytes, asyncio.Future]] = []
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        # strong refs to in-flight flush tasks (event loop keeps only weak
        # refs; a GC'd flush would strand every pending preverify future)
        self._flush_tasks: set = set()
        self._cache: "collections.OrderedDict[bytes, bool]" = collections.OrderedDict()
        self.stats = collections.Counter()

    # -- sync side (VoteSet.add_vote, single-writer loop) --------------------

    def verify(self, pub, msg: bytes, sig: bytes) -> bool:
        """Byte-identical to pub.verify_signature; consumes a cached verdict
        when the reactor already pre-verified this exact (pk, msg, sig)."""
        key = self._key(pub.bytes(), msg, sig)
        hit = self._cache.pop(key, None)
        if hit is not None:
            self.stats["cache_hits"] += 1
            return hit
        self.stats["sync_host_sigs"] += 1
        return pub.verify_signature(msg, sig)

    # -- async side (reactor per-peer tasks) ---------------------------------

    async def preverify(self, pub, msg: bytes, sig: bytes) -> bool:
        """Micro-batched verification; resolves when this item's batch does."""
        from . import Ed25519PubKey

        if not isinstance(pub, Ed25519PubKey):
            # rare key types never ride the ed25519 kernel (and must not
            # poison the cache with a wrong-scheme verdict); off the loop so
            # a flood of odd keys can't stall peer dispatch and timers
            self.stats["non_ed25519"] += 1
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None, pub.verify_signature, msg, sig)
        pk = pub.bytes()
        key = self._key(pk, msg, sig)
        cached = self._cache.get(key)
        if cached is not None:
            self.stats["cache_hits_pre"] += 1
            return cached
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((key, pk, msg, sig, fut))
        if len(self._pending) >= self.max_batch:
            self._do_flush()
        elif self._flush_handle is None:
            self._flush_handle = loop.call_later(self.deadline_s, self._do_flush)
        return await fut

    def _do_flush(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch = self._pending
        self._pending = []
        if not batch:
            return
        t = asyncio.ensure_future(self._run_flush(batch))
        self._flush_tasks.add(t)
        t.add_done_callback(self._flush_tasks.discard)

    async def _run_flush(self, batch) -> None:
        from . import Ed25519PubKey

        n = len(batch)
        loop = asyncio.get_running_loop()
        cm = _batch.metrics
        if cm is not None:
            # depth AT flush time = the flush size plus whatever already
            # queued behind it while this coroutine was scheduled
            cm.vote_queue_depth.set(n + len(self._pending))
        t_flush0 = time.perf_counter()
        t_v0 = t_flush0  # start of the verify work actually charged
        route = "scalar"

        def _host_verify():
            # live-plane batch verified on host: zero device phases, still
            # counted (crypto/phases.py host ledger). On the device-timeout
            # path the background flush ALSO records device segments for
            # the same votes when it completes — that is real duplicated
            # work (both verifies ran), and the ledger counts work done,
            # not unique votes
            _phases.count_host("live", n)
            return [Ed25519PubKey(pk).verify_signature(m, s)
                    for _key, pk, m, s, _fut in batch]

        use_device = n >= self.min_device_batch and not self._device_warming
        if use_device and not device_breaker.allow():
            # breaker OPEN (shared with BatchVerifier): no device attempt,
            # the host scalar path keeps the vote plane verifying
            use_device = False
            self.stats["breaker_rejections"] += 1
            if cm is not None:
                cm.device_fallbacks_total.labels("breaker_open").inc()
        try:
            if use_device:
                route = "device"
                pks = [b[1] for b in batch]
                msgs = [b[2] for b in batch]
                sigs = [b[3] for b in batch]

                def _device_verify():
                    # chaos seam: an armed `device.vote_flush` site raises
                    # on the executor thread, exactly where a real kernel /
                    # relay failure would surface. The ed25519_jax import
                    # lives here too so a broken jax install takes the same
                    # host-fallback + breaker path as a runtime failure
                    # instead of failing every pending preverify future
                    faults.inject("device.vote_flush")
                    from .ed25519_jax import batch_verify_stream

                    # plane=live set INSIDE the thunk: contextvars do not
                    # follow run_in_executor onto the worker thread, and the
                    # flush's pack/dispatch/fetch must land in the phase
                    # histograms next to the sync plane's segments
                    with _phases.telemetry(plane="live"):
                        return batch_verify_stream(pks, msgs, sigs)

                dev = loop.run_in_executor(None, _device_verify)
                try:
                    out = await asyncio.wait_for(
                        asyncio.shield(dev), self.device_timeout_s)
                except asyncio.TimeoutError:
                    route = "scalar"
                    # the timeout wait is flush latency, not verify latency
                    t_v0 = time.perf_counter()
                    # liveness over throughput: verify THIS batch on host
                    # now; let the (probably compiling) device call finish
                    # in the background and re-enable the device path then
                    self._device_warming = True
                    device_breaker.record_failure()

                    def _device_ready(f):
                        self._device_warming = False
                        if not f.cancelled() and f.exception() is not None:
                            # consume it: the batch was already host-verified,
                            # and an unretrieved exception would dump a
                            # traceback at GC on a consensus-critical node
                            logger.info("background device flush failed "
                                        "after timeout fallback: %s",
                                        f.exception())

                    dev.add_done_callback(_device_ready)
                    self.stats["device_timeouts"] += 1
                    self.stats["host_batches"] += 1
                    self.stats["host_sigs"] += n
                    if cm is not None:
                        cm.device_fallbacks_total.labels(
                            "device_timeout").inc()
                    results = await loop.run_in_executor(None, _host_verify)
                except Exception as e:
                    # device call FAILED (not merely slow): re-verify this
                    # batch on host — verdicts stay byte-identical, no
                    # pending preverify future is ever failed by a device
                    # error — and feed the breaker
                    route = "scalar"
                    t_v0 = time.perf_counter()
                    reason = classify_device_error(e)
                    logger.warning("device vote flush failed (%s, n=%d): %s "
                                   "— re-verifying on host", reason, n, e)
                    device_breaker.record_failure()
                    self.stats["device_errors"] += 1
                    self.stats["host_batches"] += 1
                    self.stats["host_sigs"] += n
                    if cm is not None:
                        cm.device_fallbacks_total.labels(reason).inc()
                    results = await loop.run_in_executor(None, _host_verify)
                else:
                    device_breaker.record_success()
                    self.stats["device_batches"] += 1
                    self.stats["device_sigs"] += n
                    results = [bool(v) for v in out]
            else:
                self.stats["host_batches"] += 1
                self.stats["host_sigs"] += n
                # off the event loop: even a sub-threshold flush shouldn't
                # stall peers/timers for ~ms of OpenSSL work
                results = await loop.run_in_executor(None, _host_verify)
        except Exception as e:  # pragma: no cover - defensive
            logger.exception("vote batch flush failed: %s", e)
            for _, _, _, _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        if cm is not None:
            now = time.perf_counter()
            cm.vote_flush_latency_seconds.labels(route).observe(now - t_flush0)
            cm.batch_size.labels(route, "votes").observe(n)
            cm.routing_decisions_total.labels(route, "votes").inc()
            # verify-only time (the same semantics batch.py gives this
            # series): on a device-timeout fallback t_v0 excludes the wait
            cm.verify_latency_seconds.labels(route, "votes").observe(
                now - t_v0)
        if tracer.enabled:
            tracer.instant("vote_flush", n=n, route=route)
        for (key, _pk, _m, _s, fut), ok in zip(batch, results):
            self._cache[key] = ok
            self._cache.move_to_end(key)
            if not fut.done():
                fut.set_result(ok)
        while len(self._cache) > _CACHE_CAP:
            self._cache.popitem(last=False)

    async def flush_now(self) -> None:
        """Force a flush (tests / shutdown)."""
        self._do_flush()
        await asyncio.sleep(0)

    @staticmethod
    def _key(pk: bytes, msg: bytes, sig: bytes) -> bytes:
        return b"%d|" % len(pk) + pk + b"|%d|" % len(msg) + msg + sig
