"""proxy.AppConns: the 4 logical ABCI connections (reference proxy/):
consensus, mempool, query, snapshot — local clients share one mutex
(proxy/client.go NewLocalClientCreator), remote ones get a conn each.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .abci.application import Application
from .abci.client import Client, LocalClient, SocketClient

ClientCreator = Callable[[], Client]


def local_client_creator(app: Application) -> ClientCreator:
    mtx = threading.RLock()
    return lambda: LocalClient(app, mtx)


def socket_client_creator(addr: str) -> ClientCreator:
    return lambda: SocketClient(addr)


def grpc_client_creator(addr: str) -> ClientCreator:
    """(proxy/client.go NewRemoteClientCreator transport=grpc)"""
    def make():
        from .abci.grpc import GrpcClient

        return GrpcClient(addr)
    return make


class AppConns:
    """(proxy/multi_app_conn.go)"""

    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self.consensus: Optional[Client] = None
        self.mempool: Optional[Client] = None
        self.query: Optional[Client] = None
        self.snapshot: Optional[Client] = None

    def start(self) -> None:
        self.query = self._creator()
        self.snapshot = self._creator()
        self.mempool = self._creator()
        self.consensus = self._creator()

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                c.close()
