"""proxy.AppConns: the 4 logical ABCI connections (reference proxy/):
consensus, mempool, query, snapshot.

Lock split (vs. the reference's single shared mutex,
proxy/client.go NewLocalClientCreator): the WRITER connections —
consensus and mempool — still share one RLock, because DeliverTx/Commit
and CheckTx both mutate app state and their interleaving is part of the
mempool-locked commit protocol (state/execution.py _commit). The READER
connections — query and snapshot — each get their own lock, so a slow
``/abci_query`` or a snapshot chunk read can no longer stall block
execution (and vice versa). Apps must therefore keep their query/snapshot
handlers read-only and tolerant of mid-block state (the kvstore family
snapshots the store dict atomically before iterating).

Lock order: a caller holds AT MOST ONE connection lock at a time — no
code path may acquire a second one while holding the first (the parallel
executor's apply phase enters the writer lock it already shares with the
consensus connection via RLock reentrancy, never a reader lock). This
makes lock-ordering deadlocks structurally impossible across the proxy.
"""

from __future__ import annotations

import inspect
import threading
from typing import Callable, Optional

from .abci.application import Application
from .abci.client import Client, LocalClient, SocketClient

#: creators may accept the connection role ("consensus" | "mempool" |
#: "query" | "snapshot") to pick per-role locking/transport; zero-arg
#: creators are still honored (every connection then shares whatever the
#: creator closes over)
ClientCreator = Callable[..., Client]

#: roles that mutate app state and therefore share the writer lock
WRITER_ROLES = ("consensus", "mempool")


def local_client_creator(app: Application) -> ClientCreator:
    writer_mtx = threading.RLock()
    reader_locks = {"query": threading.RLock(),
                    "snapshot": threading.RLock()}

    def make(role: str = "consensus") -> Client:
        return LocalClient(app, reader_locks.get(role, writer_mtx))

    return make


def socket_client_creator(addr: str) -> ClientCreator:
    return lambda: SocketClient(addr)


def grpc_client_creator(addr: str) -> ClientCreator:
    """(proxy/client.go NewRemoteClientCreator transport=grpc)"""
    def make():
        from .abci.grpc import GrpcClient

        return GrpcClient(addr)
    return make


class AppConns:
    """(proxy/multi_app_conn.go)"""

    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self._role_aware = _accepts_role(creator)
        self.consensus: Optional[Client] = None
        self.mempool: Optional[Client] = None
        self.query: Optional[Client] = None
        self.snapshot: Optional[Client] = None

    def _make(self, role: str) -> Client:
        if self._role_aware:
            return self._creator(role)
        return self._creator()

    def start(self) -> None:
        self.query = self._make("query")
        self.snapshot = self._make("snapshot")
        self.mempool = self._make("mempool")
        self.consensus = self._make("consensus")

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                c.close()


def _accepts_role(creator: ClientCreator) -> bool:
    try:
        sig = inspect.signature(creator)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                      p.VAR_POSITIONAL):
            return True
    return False
