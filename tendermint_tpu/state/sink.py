"""SQL event sink — the second indexer backend
(reference state/indexer/sink/psql/{psql.go,schema.sql}).

Schema parity with the reference's psql sink: ``blocks``, ``tx_results``,
``events``, ``attributes`` plus the ``event_attributes`` / ``block_events``
/ ``tx_events`` views, so operator queries written against the reference's
schema run unchanged. The storage engine is stdlib ``sqlite3`` — this image
carries no Postgres server or driver — with the DDL kept in the psql
dialect's shape (AUTOINCREMENT keys standing in for BIGSERIAL, TEXT for
TIMESTAMPTZ, BLOB for BYTEA); a psycopg2 connection could execute the
reference's schema.sql verbatim and reuse this class's DML unchanged modulo
the ``?`` placeholder style.

Like the reference sink it is write-mostly: queries go through
``get_tx_by_hash`` / ``has_block`` / ``search_tx_events`` (equality
conditions over composite keys, psql.go:239).
"""

from __future__ import annotations

import hashlib
import sqlite3
import threading
import time
from typing import Dict, List, Optional

from .txindex import TxResult

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash    VARCHAR NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);
CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      BIGINT NOT NULL REFERENCES events(rowid),
  key           VARCHAR NOT NULL,
  composite_key VARCHAR NOT NULL,
  value         VARCHAR NULL,
  UNIQUE (event_id, key)
);
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes ON
       (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""


def _split_composite(key: str) -> str:
    """'transfer.amount' -> bare key 'amount' (psql.go stores both)."""
    return key.rsplit(".", 1)[-1]


def _cond_str(value) -> str:
    """Query condition value -> the string form events store ('5', not
    '5.0'; quotes stripped)."""
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value).strip("'")


class SQLEventSink:
    """psql.go EventSink. connect string ":memory:" or a file path."""

    def __init__(self, conn_str: str, chain_id: str):
        self.chain_id = chain_id
        # the indexer pump runs on the event-bus loop; RPC queries come from
        # request handlers — one connection guarded by a lock keeps sqlite
        # happy in both
        self._conn = sqlite3.connect(conn_str, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)

    # -- write path (psql.go:142,177) --------------------------------------

    def index_block_events(self, height: int,
                           events: Dict[str, List[str]]) -> None:
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with self._lock, self._conn:
            cur = self._conn.execute(
                "INSERT INTO blocks (height, chain_id, created_at) "
                "VALUES (?, ?, ?) "
                "ON CONFLICT (height, chain_id) DO UPDATE SET created_at = ?",
                (height, self.chain_id, now, now))
            block_rowid = self._block_rowid(height)
            self._insert_events(block_rowid, None, events)

    def index_tx_events(self, results: List[TxResult]) -> None:
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with self._lock, self._conn:
            for r in results:
                self._conn.execute(
                    "INSERT OR IGNORE INTO blocks (height, chain_id, "
                    "created_at) VALUES (?, ?, ?)",
                    (r.height, self.chain_id, now))
                block_rowid = self._block_rowid(r.height)
                tx_hash = hashlib.sha256(r.tx).hexdigest().upper()
                cur = self._conn.execute(
                    'INSERT INTO tx_results (block_id, "index", created_at, '
                    "tx_hash, tx_result) VALUES (?, ?, ?, ?, ?) "
                    'ON CONFLICT (block_id, "index") DO UPDATE SET '
                    "tx_result = excluded.tx_result",
                    (block_rowid, r.index, now, tx_hash, r.to_json()))
                tx_rowid = self._conn.execute(
                    'SELECT rowid FROM tx_results WHERE block_id=? AND '
                    '"index"=?', (block_rowid, r.index)).fetchone()[0]
                # implicit tx.height, like the kv indexer (kv.go indexes it
                # for every tx so height queries always work)
                events = dict(r.events)
                events.setdefault("tx.height", [str(r.height)])
                self._insert_events(block_rowid, tx_rowid, events)

    def _block_rowid(self, height: int) -> int:
        return self._conn.execute(
            "SELECT rowid FROM blocks WHERE height=? AND chain_id=?",
            (height, self.chain_id)).fetchone()[0]

    def _insert_events(self, block_id: int, tx_id: Optional[int],
                       events: Dict[str, List[str]]) -> None:
        # events arrive flattened as composite_key -> values (the event-bus
        # form); regroup by event type for the events table
        by_type: Dict[str, List] = {}
        for ckey, values in events.items():
            etype = ckey.rsplit(".", 1)[0] if "." in ckey else ckey
            for v in values:
                by_type.setdefault(etype, []).append((ckey, v))
        for etype, attrs in by_type.items():
            cur = self._conn.execute(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                (block_id, tx_id, etype))
            event_id = cur.lastrowid
            for ckey, v in attrs:
                self._conn.execute(
                    "INSERT OR IGNORE INTO attributes (event_id, key, "
                    "composite_key, value) VALUES (?, ?, ?, ?)",
                    (event_id, _split_composite(ckey), ckey, v))

    # -- read path (psql.go:244,249,239) ------------------------------------

    def get_tx_by_hash(self, tx_hash: bytes) -> Optional[TxResult]:
        hx = tx_hash.hex().upper()
        with self._lock:
            row = self._conn.execute(
                "SELECT tx_result FROM tx_results WHERE tx_hash=? "
                "ORDER BY rowid DESC LIMIT 1", (hx,)).fetchone()
        return TxResult.from_json(row[0]) if row else None

    def has_block(self, height: int) -> bool:
        with self._lock:
            row = self._conn.execute(
                "SELECT 1 FROM blocks WHERE height=? AND chain_id=?",
                (height, self.chain_id)).fetchone()
        return row is not None

    def search_tx_events(self, composite_key: str, value: str,
                         limit: int = 100) -> List[TxResult]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT tx_results.tx_result FROM tx_results "
                "JOIN events ON events.tx_id = tx_results.rowid "
                "JOIN attributes ON attributes.event_id = events.rowid "
                "WHERE attributes.composite_key=? AND attributes.value=? "
                "ORDER BY tx_results.rowid LIMIT ?",
                (composite_key, value, limit)).fetchall()
        return [TxResult.from_json(r[0]) for r in rows]

    def search_block_events(self, composite_key: str, value: str,
                            limit: int = 100) -> List[int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT height FROM block_events "
                "WHERE composite_key=? AND value=? ORDER BY height LIMIT ?",
                (composite_key, value, limit)).fetchall()
        return [r[0] for r in rows]

    def stop(self) -> None:
        with self._lock:
            self._conn.close()

    # -- txindex-compatible seams (so the sink can serve IndexerService and
    # the /tx + tx_search RPC routes when configured as THE indexer; the
    # reference's psql sink rejects searches, psql.go:234 — equality-only
    # search is supported here because sqlite makes it free) ----------------

    def index(self, result: TxResult) -> None:
        self.index_tx_events([result])

    def get(self, tx_hash: bytes) -> Optional[TxResult]:
        return self.get_tx_by_hash(tx_hash)

    def search(self, query: str, limit: int = 100) -> List[TxResult]:
        from .txindex import Query

        q = Query(query)
        by_key: dict = {}
        result_sets = []
        for cond in q.conditions:
            if cond.op != "=":
                raise ValueError(
                    "SQL event sink supports equality conditions only")
            hits = self.search_tx_events(cond.key, _cond_str(cond.value),
                                         limit=10_000)
            by_key.update({(r.height, r.index): r for r in hits})
            result_sets.append({(r.height, r.index) for r in hits})
        if not result_sets:
            return []
        keys = sorted(set.intersection(*result_sets))
        return [by_key[k] for k in keys[:limit]]


class BlockSinkAdapter:
    """KVBlockIndexer-shaped facade over the sink (IndexerService seam)."""

    def __init__(self, sink: SQLEventSink):
        self._sink = sink

    def index(self, height: int, events: Dict[str, List[str]]) -> None:
        self._sink.index_block_events(height, events)

    def search(self, query: str, limit: int = 100) -> List[int]:
        from .txindex import Query

        q = Query(query)
        sets = []
        for cond in q.conditions:
            if cond.op != "=":
                raise ValueError(
                    "SQL event sink supports equality conditions only")
            sets.append(set(self._sink.search_block_events(
                cond.key, _cond_str(cond.value), limit=10_000)))
        if not sets:
            return []
        return sorted(set.intersection(*sets))[:limit]
