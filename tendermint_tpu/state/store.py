"""state.Store: persists State, ABCIResponses, per-height validator sets and
consensus params with change-height dedup (reference state/store.go:52).
"""

from __future__ import annotations

import json
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import List, Optional

from ..abci import types as abci
from ..libs import protowire as pw
from ..libs.db import DB, BufferedDB
from ..types import ConsensusParams, ValidatorSet
from ..types.basic import BlockID, PartSetHeader
from ..types.block import Consensus
from .state import State

_STATE_KEY = b"stateKey"


def _validators_key(h: int) -> bytes:
    return b"validatorsKey:" + str(h).encode()


# resolution floor for validator change-pointers after pruning (heights
# below it are deleted) — distinct from the materialization marker below,
# which only says "a nearby full record exists", never "data is gone"
_VALS_CHECKPOINT_KEY = b"validatorsCheckpoint"
# latest interval-materialized full record (see _VALS_MATERIALIZE_INTERVAL)
_VALS_MATERIALIZED_KEY = b"validatorsMaterialized"

# materialize a full set at least this often even without changes: loads
# roll proposer priorities forward from the pointer target, so unbounded
# pointer runs make load_validators O(height since change) — the reference
# bounds the same walk with valSetCheckpointInterval (store.go:36; its
# 100k interval tolerates huge rolls because Go's increment is ~ns — in
# Python a short interval keeps the per-load roll under ~16 increments
# while a full write every 16 heights amortizes to noise)
_VALS_MATERIALIZE_INTERVAL = 16


def _params_key(h: int) -> bytes:
    return b"consensusParamsKey:" + str(h).encode()


def _abci_responses_key(h: int) -> bytes:
    return b"abciResponsesKey:" + str(h).encode()


@dataclass
class ABCIResponses:
    """Responses persisted per height (reference proto/tendermint/state ABCIResponses).

    ORDERING CONTRACT: ``deliver_txs[i]`` is the response to
    ``block.data.txs[i]`` — block position, not execution order. Everything
    downstream leans on the index: ``results_hash()`` merkle-hashes the
    list positionally (committed into the next header), event publication
    pairs ``txs[i]`` with ``deliver_txs[i]`` (execution.py fire_events),
    the tx indexer keys on (height, i), and mempool.update consumes the
    list zip-wise. Any executor — serial or parallel (state/parallel.py) —
    must assemble this list by block index; tests/test_parallel_exec.py
    pins the contract differentially."""

    deliver_txs: List[abci.ResponseDeliverTx] = field(default_factory=list)
    end_block: Optional[abci.ResponseEndBlock] = None
    begin_block: Optional[abci.ResponseBeginBlock] = None

    def results_hash(self) -> bytes:
        return abci.last_results_hash(self.deliver_txs)

    def to_json(self) -> bytes:
        from ..abci.client import _to_jsonable

        return json.dumps(_to_jsonable({
            "deliver_txs": self.deliver_txs,
            "end_block": self.end_block,
            "begin_block": self.begin_block,
        })).encode()

    @staticmethod
    def from_json(raw: bytes) -> "ABCIResponses":
        from ..abci.client import _from_jsonable, _rebuild

        d = _from_jsonable(json.loads(raw.decode()))
        return ABCIResponses(
            deliver_txs=[_rebuild(abci.ResponseDeliverTx, x) for x in d.get("deliver_txs") or []],
            end_block=_rebuild(abci.ResponseEndBlock, d.get("end_block")),
            begin_block=_rebuild(abci.ResponseBeginBlock, d.get("begin_block")),
        )


# -- State <-> JSON (storage format is ours; byte parity not required here) --

def _state_to_json(s: State) -> bytes:
    return json.dumps({
        "chain_id": s.chain_id,
        "initial_height": s.initial_height,
        "version_block": s.version.block,
        "version_app": s.version.app,
        "last_block_height": s.last_block_height,
        "last_block_id": {
            "hash": s.last_block_id.hash.hex(),
            "total": s.last_block_id.part_set_header.total,
            "psh_hash": s.last_block_id.part_set_header.hash.hex(),
        },
        "last_block_time_ns": s.last_block_time_ns,
        "next_validators": s.next_validators.encode().hex() if s.next_validators else None,
        "validators": s.validators.encode().hex() if s.validators else None,
        "last_validators": s.last_validators.encode().hex() if s.last_validators else None,
        "last_height_validators_changed": s.last_height_validators_changed,
        "consensus_params": s.consensus_params.encode().hex(),
        "last_height_consensus_params_changed": s.last_height_consensus_params_changed,
        "last_results_hash": s.last_results_hash.hex(),
        "app_hash": s.app_hash.hex(),
    }).encode()


def _state_from_json(raw: bytes) -> State:
    d = json.loads(raw.decode())

    def vs(key):
        return ValidatorSet.decode(bytes.fromhex(d[key])) if d.get(key) else None

    return State(
        chain_id=d["chain_id"],
        initial_height=d["initial_height"],
        version=Consensus(d["version_block"], d["version_app"]),
        last_block_height=d["last_block_height"],
        last_block_id=BlockID(
            bytes.fromhex(d["last_block_id"]["hash"]),
            PartSetHeader(d["last_block_id"]["total"],
                          bytes.fromhex(d["last_block_id"]["psh_hash"])),
        ),
        last_block_time_ns=d["last_block_time_ns"],
        next_validators=vs("next_validators"),
        validators=vs("validators"),
        last_validators=vs("last_validators"),
        last_height_validators_changed=d["last_height_validators_changed"],
        consensus_params=ConsensusParams.decode(bytes.fromhex(d["consensus_params"])),
        last_height_consensus_params_changed=d["last_height_consensus_params_changed"],
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
    )


class StateStore:
    def __init__(self, db: DB):
        self._db = db
        # one-slot decode cache for the latest full validator record:
        # (height, pristine ValidatorSet). Per-block ABCI BeginBlock loads
        # the prior height's set — hex+proto decoding 1000 validators every
        # block was a top apply-plane cost. All record writes go through
        # this class, so the slot is refreshed at every materialization.
        self._full_record_cache: "Optional[tuple]" = None

    @contextmanager
    def window_batch(self):
        """Stage every write in the scope into ONE DB write-batch, flushed
        at exit (success or error — staged writes describe blocks whose
        ABCI commit already happened). Reads inside the scope observe the
        staged writes (load_validators follows pointer records written
        earlier in the same fast-sync window). Reentrant: nested scopes
        join the outer batch."""
        if isinstance(self._db, BufferedDB):
            yield self
            return
        buf = BufferedDB(self._db)
        self._db = buf
        try:
            yield self
        finally:
            # flush BEFORE unhooking: a failed flush keeps the staged
            # window reachable as self._db (no silent drop of records the
            # app already handled)
            buf.flush()
            self._db = buf.base

    # -- state --

    def load(self) -> Optional[State]:
        raw = self._db.get(_STATE_KEY)
        return _state_from_json(raw) if raw is not None else None

    def save(self, state: State) -> None:
        """Persist state + next validators + params at their change heights
        (state/store.go:175)."""
        next_height = state.last_block_height + 1
        if state.last_block_height == 0:  # genesis bootstrap
            next_height = state.initial_height
            self._save_validators(next_height, state.validators)
        self._save_validators(next_height + 1, state.next_validators,
                              last_changed=state.last_height_validators_changed)
        self._save_params(next_height, state.consensus_params,
                          state.last_height_consensus_params_changed)
        self._db.set(_STATE_KEY, _state_to_json(state))

    def bootstrap(self, state: State) -> None:
        """Seed stores from an out-of-band trusted state — state sync
        (state/store.go Bootstrap)."""
        # reference store.go Bootstrap: height := LastBlockHeight+1 (or
        # InitialHeight at genesis); LastValidators validate block height-1,
        # Validators block height, NextValidators block height+1; params for
        # block height
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        if height > 1 and state.last_validators is not None \
                and state.last_validators.size() > 0:
            self._save_validators(height - 1, state.last_validators)
        self._save_validators(height, state.validators)
        self._save_validators(height + 1, state.next_validators)
        self._save_params(height, state.consensus_params,
                          state.last_height_consensus_params_changed)
        self._db.set(_STATE_KEY, _state_to_json(state))

    # -- validators (with change-height dedup, state/store.go:289) --

    def _save_validators(self, height: int, vals: ValidatorSet,
                         last_changed: Optional[int] = None) -> None:
        """Full set only at its change height; unchanged heights store just
        the pointer (saveValidatorsInfo, store.go:289) — re-encoding a
        1000-validator set every block was ~1/3 of the store's per-block
        cost, for bytes that never change. A pointer is only written when
        its target record actually holds a full set: rollback can rewrite
        change heights such that the natural target is itself a pointer,
        and a pointer chain would make the height unloadable."""
        if last_changed is None or last_changed >= height:
            last_changed = height
        target_h = (self._resolve_target(last_changed, height)
                    if height > last_changed else height)
        if (height > target_h
                and height - target_h < _VALS_MATERIALIZE_INTERVAL):
            target = self._db.get(_validators_key(target_h))
            if target is not None and b'"set"' in target:
                self._db.set(_validators_key(height), json.dumps(
                    {"last_changed": target_h}).encode())
                return
            # unresolvable target: materialize (self-healing, no chains)
        self._db.set(_validators_key(height), json.dumps({
            "last_changed": height, "set": vals.encode().hex(),
        }).encode())
        # copy: the caller keeps mutating its live set (priority rotation)
        self._full_record_cache = (height, vals.copy())
        if height > last_changed:
            # interval materialization: record this nearby full set so
            # subsequent pointers (and loads) target it instead of rolling
            # priorities all the way from the original change height
            self._db.set(_VALS_MATERIALIZED_KEY, str(height).encode())

    def _resolve_target(self, last_changed: int, height: int) -> int:
        """The best full-record height for a pointer valid at ``height``:
        the highest of the declared change height, the prune floor, and the
        latest materialized record that does not exceed ``height`` (a
        prune floor above ``height`` means the data is simply gone; a
        materialization above it must be ignored, records below it still
        exist)."""
        best = last_changed
        raw = self._db.get(_VALS_CHECKPOINT_KEY)
        if raw is not None and best < int(raw) <= height:
            best = int(raw)
        raw = self._db.get(_VALS_MATERIALIZED_KEY)
        if raw is not None and best < int(raw) <= height:
            best = int(raw)
        return best

    def load_validators(self, height: int) -> Optional[ValidatorSet]:
        """(loadValidators, store.go:249) follow the change pointer, then
        roll proposer priorities forward to the requested height."""
        raw = self._db.get(_validators_key(height))
        if raw is None:
            return None
        d = json.loads(raw.decode())
        if "set" in d:
            return ValidatorSet.decode(bytes.fromhex(d["set"]))
        declared = int(d["last_changed"])
        last_changed = self._resolve_target(declared, height)
        vals = self._load_full_record(last_changed)
        if vals is None and last_changed != declared:
            # the resolved target (checkpoint/materialization marker) does
            # not hold a full record — stale marker, interrupted prune:
            # fall back to the pointer's own declared change height rather
            # than reporting a retained height as unloadable
            last_changed = declared
            vals = self._load_full_record(declared)
        if vals is None:
            return None
        vals.increment_proposer_priority(height - last_changed)
        return vals

    def _load_full_record(self, height: int) -> Optional[ValidatorSet]:
        """Decode the full validator record at ``height`` (None when the
        record is missing or a pointer); serves the hot per-block load from
        the one-slot cache when possible."""
        cached = self._full_record_cache
        if cached is not None and cached[0] == height:
            return cached[1].copy()
        raw = self._db.get(_validators_key(height))
        if raw is None:
            return None
        d = json.loads(raw.decode())
        if "set" not in d:
            return None
        vals = ValidatorSet.decode(bytes.fromhex(d["set"]))
        self._full_record_cache = (height, vals.copy())
        return vals

    # -- consensus params --

    def _save_params(self, height: int, params: ConsensusParams, last_changed: int) -> None:
        self._db.set(_params_key(height), json.dumps({
            "last_changed": last_changed, "params": params.encode().hex(),
        }).encode())

    def load_consensus_params(self, height: int) -> Optional[ConsensusParams]:
        raw = self._db.get(_params_key(height))
        if raw is None:
            return None
        d = json.loads(raw.decode())
        return ConsensusParams.decode(bytes.fromhex(d["params"]))

    # -- abci responses --

    def save_abci_responses(self, height: int, responses: ABCIResponses) -> None:
        self._db.set(_abci_responses_key(height), responses.to_json())

    def load_abci_responses(self, height: int) -> Optional[ABCIResponses]:
        raw = self._db.get(_abci_responses_key(height))
        return ABCIResponses.from_json(raw) if raw is not None else None

    def prune_states(self, retain_height: int) -> None:
        """Drop per-height records below retain_height (state/store.go PruneStates)."""
        # checkpoint first: validator records at/above the retain height may
        # be change-pointers into the range being pruned — materialize a
        # full set at retain_height and record it as the resolution floor
        # (the reference's loadValidators clamps pointer targets to its
        # checkpoint the same way, store.go lastStoredHeightFor). Skip the
        # decode/re-encode when the record is already full: prune runs per
        # commit on retention-configured nodes, and re-materializing every
        # block would re-add the cost the pointer scheme removed.
        raw = self._db.get(_validators_key(retain_height))
        record_is_full = raw is not None and b'"set"' in raw
        if raw is not None and not record_is_full:
            keep = self.load_validators(retain_height)
            if keep is not None:
                self._db.set(_validators_key(retain_height), json.dumps({
                    "last_changed": retain_height,
                    "set": keep.encode().hex(),
                }).encode())
                self._full_record_cache = (retain_height, keep)
                record_is_full = True
        # the checkpoint is a resolution floor: writing it while the
        # retain-height record is still a pointer (materialization failed)
        # would clamp every later pointer onto a non-full record and make
        # retained heights unloadable — only advance it once the full
        # record is confirmed on disk
        if record_is_full:
            self._db.set(_VALS_CHECKPOINT_KEY, str(retain_height).encode())
        if (self._full_record_cache is not None
                and self._full_record_cache[0] < retain_height):
            self._full_record_cache = None  # record about to be deleted
        deletes: List[bytes] = []
        for key_fn in (_validators_key, _params_key, _abci_responses_key):
            prefix = key_fn(0).rsplit(b":", 1)[0] + b":"
            for k, _ in self._db.iterate_prefix(prefix):
                try:
                    h = int(k.rsplit(b":", 1)[1])
                except ValueError:
                    continue
                if h < retain_height:
                    deletes.append(k)
        if deletes:
            self._db.write_batch([], deletes)
