"""State + execution tier (reference state/, SURVEY.md §2.6)."""

from .state import State, median_time, state_from_genesis  # noqa: F401
from .store import StateStore, ABCIResponses  # noqa: F401
from .execution import BlockExecutor  # noqa: F401
from .validation import validate_block  # noqa: F401
