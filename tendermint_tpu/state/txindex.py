"""Tx + block event indexing (reference state/txindex/indexer_service.go,
state/txindex/kv/kv.go, state/indexer/block/kv/):

an IndexerService subscribes to the EventBus (Tx + NewBlockHeader events)
and writes a KV index that powers the ``tx``, ``tx_search`` and
``block_search`` RPC routes. Queries reuse the pubsub query language
(libs/pubsub.Query — same grammar the reference compiles from query.peg).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..libs.db import DB
from ..libs.pubsub import Query
from ..types import events as tme

logger = logging.getLogger("tmtpu.txindex")

_TX_HASH_PREFIX = b"tx/h/"     # tx hash -> stored result
_TX_EVENT_PREFIX = b"tx/e/"    # key/value/height/index -> tx hash
_BLOCK_EVENT_PREFIX = b"blk/e/"  # key/value/height -> height


@dataclass
class TxResult:
    """(proto abci.TxResult) what the kv indexer persists per tx."""

    height: int
    index: int
    tx: bytes
    code: int
    data: bytes
    log: str
    gas_wanted: int
    gas_used: int
    events: Dict[str, List[str]] = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps({
            "height": self.height, "index": self.index, "tx": self.tx.hex(),
            "code": self.code, "data": self.data.hex(), "log": self.log,
            "gas_wanted": self.gas_wanted, "gas_used": self.gas_used,
            "events": self.events,
        }).encode()

    @staticmethod
    def from_json(raw: bytes) -> "TxResult":
        d = json.loads(raw)
        return TxResult(d["height"], d["index"], bytes.fromhex(d["tx"]),
                        d["code"], bytes.fromhex(d["data"]), d["log"],
                        d["gas_wanted"], d["gas_used"], d.get("events", {}))


class KVTxIndexer:
    """(state/txindex/kv/kv.go TxIndex)"""

    def __init__(self, db: DB):
        self.db = db

    def index(self, result: TxResult) -> None:
        tx_hash = hashlib.sha256(result.tx).digest()
        self.db.set(_TX_HASH_PREFIX + tx_hash, result.to_json())
        for key, values in result.events.items():
            for v in values:
                self.db.set(self._event_key(key, v, result.height, result.index),
                            tx_hash)
        # implicit tx.height index (kv.go indexes it always)
        self.db.set(self._event_key(tme.TX_HEIGHT_KEY, str(result.height),
                                    result.height, result.index), tx_hash)

    def get(self, tx_hash: bytes) -> Optional[TxResult]:
        raw = self.db.get(_TX_HASH_PREFIX + tx_hash)
        return TxResult.from_json(raw) if raw else None

    def search(self, query: str, limit: int = 100) -> List[TxResult]:
        """(kv.go Search) intersect per-condition hash sets; '=' only fast
        path, plus range ops evaluated against the stored event values."""
        q = Query(query)
        result_sets: List[set] = []
        for cond in q.conditions:
            matches = set()
            prefix = _TX_EVENT_PREFIX + cond.key.encode() + b"/"
            for k, v in self.db.iterate(prefix, prefix + b"\xff"):
                parts = k[len(prefix):].rsplit(b"/", 2)
                if len(parts) != 3:
                    continue
                value = parts[0].decode()
                if _cond_matches(cond, value):
                    matches.add(v)
            result_sets.append(matches)
        if not result_sets:
            return []
        hashes = set.intersection(*result_sets)
        out = [self.get(h) for h in hashes]
        out = [r for r in out if r is not None]
        out.sort(key=lambda r: (r.height, r.index))
        return out[:limit]

    @staticmethod
    def _event_key(key: str, value: str, height: int, index: int) -> bytes:
        return (_TX_EVENT_PREFIX + key.encode() + b"/" + value.encode()
                + b"/" + str(height).encode() + b"/" + str(index).encode())


class KVBlockIndexer:
    """(state/indexer/block/kv) indexes begin/end-block events by height."""

    def __init__(self, db: DB):
        self.db = db

    def index(self, height: int, events: Dict[str, List[str]]) -> None:
        self.db.set(_BLOCK_EVENT_PREFIX + b"height/%d" % height,
                    str(height).encode())
        for key, values in events.items():
            for v in values:
                self.db.set(
                    _BLOCK_EVENT_PREFIX + key.encode() + b"/" + v.encode()
                    + b"/%d" % height, str(height).encode())

    def search(self, query: str, limit: int = 100) -> List[int]:
        q = Query(query)
        result_sets: List[set] = []
        for cond in q.conditions:
            matches = set()
            prefix = _BLOCK_EVENT_PREFIX + cond.key.encode() + b"/"
            for k, v in self.db.iterate(prefix, prefix + b"\xff"):
                value = k[len(prefix):].rsplit(b"/", 1)[0].decode()
                if _cond_matches(cond, value):
                    matches.add(int(v))
            result_sets.append(matches)
        if not result_sets:
            return []
        heights = sorted(set.intersection(*result_sets))
        return heights[:limit]


def _cond_matches(cond, value: str) -> bool:
    if cond.op == "EXISTS":
        return True
    if cond.op == "=":
        if isinstance(cond.value, (int, float)):
            try:
                return float(value) == float(cond.value)
            except ValueError:
                return False
        return value == str(cond.value).strip("'")
    if cond.op == "CONTAINS":
        return str(cond.value).strip("'") in value
    try:
        lhs = float(value)
        rhs = float(cond.value)
    except (TypeError, ValueError):
        return False
    return {"<": lhs < rhs, "<=": lhs <= rhs,
            ">": lhs > rhs, ">=": lhs >= rhs}[cond.op]


class IndexerService:
    """(state/txindex/indexer_service.go) EventBus → indexers pump."""

    def __init__(self, tx_indexer: KVTxIndexer, block_indexer: KVBlockIndexer,
                 event_bus):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self._tasks: List[asyncio.Task] = []

    async def start(self) -> None:
        tx_sub = self.event_bus.subscribe("indexer-tx", tme.QUERY_TX,
                                          out_capacity=1000)
        blk_sub = self.event_bus.subscribe("indexer-blk",
                                           tme.QUERY_NEW_BLOCK_HEADER,
                                           out_capacity=1000)
        self._tasks = [asyncio.create_task(self._pump_tx(tx_sub)),
                       asyncio.create_task(self._pump_block(blk_sub))]

    async def stop(self) -> None:
        self.event_bus.unsubscribe_all("indexer-tx")
        self.event_bus.unsubscribe_all("indexer-blk")
        for t in self._tasks:
            t.cancel()

    async def _pump_tx(self, sub) -> None:
        from ..libs.pubsub import SubscriptionCanceled

        try:
            while True:
                msg = await sub.next()
                ev = msg.data
                r = ev.result
                self.tx_indexer.index(TxResult(
                    height=ev.height, index=ev.index, tx=ev.tx,
                    code=getattr(r, "code", 0), data=getattr(r, "data", b""),
                    log=getattr(r, "log", ""),
                    gas_wanted=getattr(r, "gas_wanted", 0),
                    gas_used=getattr(r, "gas_used", 0),
                    events=msg.events))
        except (SubscriptionCanceled, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("tx indexer pump died")

    async def _pump_block(self, sub) -> None:
        from ..libs.pubsub import SubscriptionCanceled

        try:
            while True:
                msg = await sub.next()
                header = getattr(msg.data, "header", None)
                height = header.height if header else 0
                self.block_indexer.index(height, msg.events)
        except (SubscriptionCanceled, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("block indexer pump died")
