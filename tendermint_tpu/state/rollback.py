"""One-height state rollback (reference state/rollback.go Rollback):
re-derives the state at height H-1 from the stores so the node re-applies
block H — the escape hatch for an app-hash divergence after an app bug fix.
"""

from __future__ import annotations

from typing import Tuple

from .state import State
from .store import StateStore


class RollbackError(Exception):
    pass


def rollback_state(block_store, state_store: StateStore) -> Tuple[int, bytes]:
    """-> (rolled-back height, app_hash). Mirrors rollback.go semantics,
    including the early return when only the block store ran ahead."""
    invalid = state_store.load()
    if invalid is None:
        raise RollbackError("no state found")
    height = block_store.height()

    # state save and block save are not atomic: if only the block store ran
    # ahead, restart replay reconciles — nothing to roll back
    if height == invalid.last_block_height + 1:
        return invalid.last_block_height, invalid.app_hash
    if height != invalid.last_block_height:
        raise RollbackError(
            f"statestore height ({invalid.last_block_height}) is not one "
            f"below or equal to blockstore height ({height})")

    rollback_height = invalid.last_block_height - 1
    rollback_block = block_store.load_block_meta(rollback_height)
    if rollback_block is None:
        raise RollbackError(f"block at height {rollback_height} not found")
    latest_block = block_store.load_block_meta(invalid.last_block_height)
    if latest_block is None:
        raise RollbackError(
            f"block at height {invalid.last_block_height} not found")

    prev_last_validators = state_store.load_validators(rollback_height)
    if prev_last_validators is None:
        raise RollbackError(f"no validators at height {rollback_height}")
    prev_params = state_store.load_consensus_params(rollback_height + 1)
    if prev_params is None:
        # the reference errors here (state/rollback.go); silently carrying the
        # invalid state's params would resurrect a post-change param set
        raise RollbackError(
            f"no consensus params at height {rollback_height + 1}")

    val_change = invalid.last_height_validators_changed
    if val_change == invalid.last_block_height + 1:
        val_change = rollback_height + 1
    params_change = invalid.last_height_consensus_params_changed
    if params_change == invalid.last_block_height + 1:
        params_change = rollback_height + 1

    rolled = State(
        chain_id=invalid.chain_id,
        initial_height=invalid.initial_height,
        version=invalid.version,
        last_block_height=rollback_block.header.height,
        last_block_id=rollback_block.block_id,
        last_block_time_ns=rollback_block.header.time_ns,
        next_validators=invalid.validators,
        validators=invalid.last_validators,
        last_validators=prev_last_validators,
        last_height_validators_changed=val_change,
        consensus_params=prev_params,
        last_height_consensus_params_changed=params_change,
        last_results_hash=latest_block.header.last_results_hash,
        app_hash=latest_block.header.app_hash,
    )
    state_store.save(rolled)
    return rolled.last_block_height, rolled.app_hash
