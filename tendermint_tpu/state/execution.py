"""BlockExecutor — the only entry for committing a block
(reference state/execution.go:131 ApplyBlock; SURVEY.md §3.3).

Pipeline: validate → BeginBlock → DeliverTx* → EndBlock → persist responses →
apply validator updates → mempool-locked Commit → save state → fire events.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from .. import crypto
from ..abci import types as abci
from ..abci.client import Client
from ..store import BlockStore
from ..types import ConsensusParams, ValidatorSet
from ..types.basic import BlockID, BlockIDFlag
from ..types.block import Block, Commit
from ..types.evidence import Evidence
from ..types.part_set import PartSet
from ..types.validator import Validator
from .state import State
from .store import ABCIResponses, StateStore
from .validation import validate_block

logger = logging.getLogger("tmtpu.state")


class Mempool:
    """The surface BlockExecutor needs (reference mempool/mempool.go:30).

    ``reap_max_bytes_max_gas`` — the proposal-creation call site below —
    must be DETERMINISTIC in the pool's contents: the CList port reaps
    insertion order, the sharded-lane pool (mempool/ingest.py) a merged
    (priority desc, arrival asc) order; either way two reaps over the
    same residents yield the same block. ``update`` runs under
    ``lock()``/``unlock()`` held across the whole commit (post-commit
    recheck included), so admissions racing a commit serialize behind
    it."""

    def lock(self) -> None: ...
    def unlock(self) -> None: ...
    def flush_app_conn(self) -> None: ...
    def update(self, height: int, txs: List[bytes],
               deliver_tx_responses: List[abci.ResponseDeliverTx],
               pre_check=None, post_check=None) -> None: ...
    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return []
    def size(self) -> int:
        return 0


class EvidencePool:
    """(reference state/services.go EvidencePool)"""

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        return [], 0

    def add_evidence(self, ev: Evidence) -> None: ...
    def check_evidence(self, evidence: List[Evidence]) -> None: ...
    def update(self, state: State, evidence: List[Evidence]) -> None: ...
    def report_conflicting_votes(self, vote_a, vote_b) -> None: ...


class EmptyEvidencePool(EvidencePool):
    pass


class NoOpMempool(Mempool):
    pass


class BlockExecutor:
    metrics = None  # StateMetrics, wired by the node

    def __init__(self, state_store: StateStore, proxy_app_consensus: Client,
                 mempool: Mempool, evidence_pool: EvidencePool,
                 block_store: Optional[BlockStore] = None, event_bus=None,
                 exec_config=None):
        self.state_store = state_store
        self.proxy_app = proxy_app_consensus
        self.mempool = mempool
        self.evpool = evidence_pool
        self.block_store = block_store
        self.event_bus = event_bus
        # execution.version: "v1" = optimistic parallel (state/parallel.py)
        # with automatic serial fallback; "v0"/None = the serial spec path
        self.exec_config = exec_config
        self._parallel = None
        if exec_config is not None and exec_config.version == "v1":
            from .parallel import ParallelExecutor

            self._parallel = ParallelExecutor(
                workers=exec_config.workers,
                min_parallel_txs=exec_config.min_parallel_txs)

    def _exec_block(self, block: Block, state: State) -> ABCIResponses:
        """The execute stage: parallel when configured AND eligible,
        else the serial spec — outputs byte-identical either way."""
        if self._parallel is not None:
            if self.metrics is not None:
                self._parallel.metrics = self.metrics
            resp = self._parallel.try_exec_block(
                self.proxy_app, block, self.state_store,
                state.initial_height)
            if resp is not None:
                return resp
        return exec_block_on_proxy_app(
            self.proxy_app, block, self.state_store, state.initial_height)

    # -- proposal creation (execution.go:94 CreateProposalBlock) -----------

    def create_proposal_block(self, height: int, state: State, commit: Optional[Commit],
                              proposer_addr: bytes) -> Tuple[Block, PartSet]:
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence, ev_size = self.evpool.pending_evidence(
            state.consensus_params.evidence.max_bytes)
        max_data_bytes = max_data_bytes_for(max_bytes, ev_size, state.validators.size())
        txs = self.mempool.reap_max_bytes_max_gas(max_data_bytes, max_gas)
        return state.make_block(height, txs, commit, evidence, proposer_addr)

    # -- validation --------------------------------------------------------

    def validate_block(self, state: State, block: Block) -> None:
        validate_block(state, block)
        self.evpool.check_evidence(block.evidence)

    # -- the commit pipeline (execution.go:131 ApplyBlock) -----------------

    def apply_block(self, state: State, block_id: BlockID, block: Block) -> Tuple[State, int]:
        """Returns (new_state, retain_height)."""
        from ..libs.trace import tracer as _tracer

        # exception-safe span: a rejected block must still leave its event
        with _tracer.span("apply_block", height=block.header.height,
                          n_txs=len(block.data.txs)):
            return self._apply_block_inner(state, block_id, block)

    def _apply_block_inner(self, state: State, block_id: BlockID,
                           block: Block) -> Tuple[State, int]:
        import time as _time

        from ..crypto import phases
        from ..libs.fail import fail_point

        _t0 = _time.perf_counter()
        # exec-plane phase record (plane="exec", device="app"): validate
        # maps to pack, execute to dispatch, commit+persist to fetch — so
        # phase_breakdown() can split exposed-execute from exposed-verify
        # wall share under the blocksync pipeline.
        _seg = phases.Segment(sigs=len(block.data.txs),
                              chunk=len(block.data.txs), device="app",
                              plane="exec", height=block.header.height)
        _seg.begin()
        try:
            new_state, retain = self._apply_block_phases(
                state, block_id, block, _seg, fail_point)
        except BaseException:
            _seg.abandon()
            raise
        if self.metrics is not None:
            self.metrics.block_processing_time.observe(
                _time.perf_counter() - _t0)
        return new_state, retain

    def _apply_block_phases(self, state: State, block_id: BlockID,
                            block: Block, _seg, fail_point) -> Tuple[State, int]:
        self.validate_block(state, block)
        fail_point("execution.before_exec_block")  # (execution.go:149)
        _seg.pack_done()

        abci_responses = self._exec_block(block, state)
        _seg.dispatched()

        self.state_store.save_abci_responses(block.header.height, abci_responses)

        raw_updates = (abci_responses.end_block.validator_updates
                       if abci_responses.end_block else [])
        validate_validator_updates(raw_updates, state.consensus_params)
        validator_updates = [validator_update_to_validator(vu)
                             for vu in raw_updates]

        new_state = update_state(state, block_id, block, abci_responses, validator_updates)

        # Lock mempool, commit app state, update mempool (execution.go:211).
        app_hash, retain_height = self._commit(new_state, block,
                                               abci_responses.deliver_txs)

        self.evpool.update(new_state, block.evidence)

        new_state.app_hash = app_hash
        self.state_store.save(new_state)
        _seg.fetched()

        fail_point("execution.after_state_save")  # (execution.go:196)
        if self.event_bus is not None:
            # event publication order is the ABCIResponses ordering
            # contract: per-tx events index deliver_txs by block position
            fire_events(self.event_bus, block, block_id, abci_responses, validator_updates)

        return new_state, retain_height

    def _commit(self, state: State, block: Block,
                deliver_tx_responses: List[abci.ResponseDeliverTx]) -> Tuple[bytes, int]:
        self.mempool.lock()
        try:
            self.mempool.flush_app_conn()
            res = self.proxy_app.commit()
            logger.info("committed state: height=%d txs=%d app_hash=%s",
                        block.header.height, len(block.data.txs), res.data.hex())
            self.mempool.update(block.header.height, block.data.txs,
                                deliver_tx_responses)
            return res.data, res.retain_height
        finally:
            self.mempool.unlock()


# -- free functions mirroring execution.go ----------------------------------

def exec_block_on_proxy_app(proxy_app: Client, block: Block, state_store: StateStore,
                            initial_height: int) -> ABCIResponses:
    """(execution.go:259) BeginBlock → DeliverTx* → EndBlock."""
    commit_info = get_begin_block_validator_info(block, state_store, initial_height)
    byz_vals = [ev_to_abci(ev) for ev in block.evidence]

    begin = proxy_app.begin_block(abci.RequestBeginBlock(
        hash=block.hash() or b"", header=block.header,
        last_commit_info=commit_info, byzantine_validators=byz_vals))
    deliver_txs = [proxy_app.deliver_tx(abci.RequestDeliverTx(tx=tx))
                   for tx in block.data.txs]
    invalid = sum(1 for r in deliver_txs if not r.is_ok())
    if invalid:
        logger.debug("executed block height=%d valid_txs=%d invalid_txs=%d",
                     block.header.height, len(deliver_txs) - invalid, invalid)
    end = proxy_app.end_block(abci.RequestEndBlock(height=block.header.height))
    return ABCIResponses(deliver_txs=deliver_txs, end_block=end, begin_block=begin)


def get_begin_block_validator_info(block: Block, state_store: StateStore,
                                   initial_height: int) -> abci.LastCommitInfo:
    """(execution.go getBeginBlockValidatorInfo)"""
    votes: List[abci.VoteInfo] = []
    if block.header.height > initial_height:
        last_val_set = state_store.load_validators(block.header.height - 1)
        if last_val_set is None:
            raise ValueError(f"no validator set at height {block.header.height - 1}")
        commit_size = block.last_commit.size()
        vals_size = last_val_set.size()
        if commit_size != vals_size:
            raise ValueError(
                f"commit size ({commit_size}) doesn't match valset length ({vals_size}) "
                f"at height {block.header.height}")
        aggregated = hasattr(block.last_commit, "agg_sig")
        for i, val in enumerate(last_val_set.validators):
            if aggregated:
                signed = block.last_commit.signers.get_index(i)
            else:
                signed = not block.last_commit.signatures[i].absent()
            votes.append(abci.VoteInfo(
                validator=abci.ABCIValidator(val.address, val.voting_power),
                signed_last_block=signed))
    round_ = block.last_commit.round if block.last_commit else 0
    return abci.LastCommitInfo(round=round_, votes=votes)


def ev_to_abci(ev: Evidence) -> abci.ABCIEvidence:
    from ..types.evidence import DuplicateVoteEvidence, LightClientAttackEvidence

    if isinstance(ev, DuplicateVoteEvidence):
        return abci.ABCIEvidence(
            type="DUPLICATE_VOTE",
            validator=abci.ABCIValidator(ev.vote_a.validator_address, ev.validator_power),
            height=ev.height(), time_ns=ev.time_ns(),
            total_voting_power=ev.total_voting_power)
    if isinstance(ev, LightClientAttackEvidence):
        return abci.ABCIEvidence(
            type="LIGHT_CLIENT_ATTACK", height=ev.height(), time_ns=ev.time_ns(),
            total_voting_power=ev.total_voting_power)
    raise ValueError(f"unknown evidence type {type(ev)}")


def validator_update_to_validator(vu: abci.ValidatorUpdate) -> Validator:
    pub = crypto.pubkey_from_type_and_bytes(vu.pub_key_type, vu.pub_key_bytes)
    return Validator(pub.address(), pub, vu.power)


def validate_validator_updates(updates: List[abci.ValidatorUpdate],
                               params: ConsensusParams) -> None:
    """(state/validation.go validateValidatorUpdates) — takes the RAW ABCI
    updates so bls12381 admissions can be held to their proof of possession:
    an aggregated chain with a dynamic validator set is exactly where a
    rogue key (pk* - sum of honest pks) would let an attacker forge
    fast-aggregate commits, so the PoP gate that genesis enforces must also
    cover every key entering via EndBlock/InitChain."""
    from ..crypto import BLS12381_TYPE
    from ..crypto import bls12381 as _bls

    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative: {vu}")
        if vu.power == 0:
            continue  # deletion
        if vu.pub_key_type not in params.validator.pub_key_types:
            raise ValueError(
                f"validator update with pubkey {vu.pub_key_bytes.hex()} is using "
                f"pubkey type {vu.pub_key_type}, which is unsupported for consensus")
        if vu.pub_key_type == BLS12381_TYPE:
            # Every bls12381 admission (including a power change for a
            # sitting validator) must carry a valid PoP.  Deliberately NOT
            # short-circuited through is_registered: that set is in-process
            # state, and a freshly restarted node (empty set) must reach the
            # same verdict as a long-running one.
            if not vu.pop:
                raise ValueError(
                    f"bls12381 validator update {vu.pub_key_bytes.hex()} has no "
                    f"proof of possession (rogue-key gate)")
            if not _bls.pop_verify(vu.pub_key_bytes, vu.pop):
                raise ValueError(
                    f"invalid bls12381 proof of possession for validator "
                    f"update {vu.pub_key_bytes.hex()}")
            # vetted above — joins the aggregation-eligible set
            _bls.register_key(vu.pub_key_bytes, vu.pop)


def update_state(state: State, block_id: BlockID, block: Block,
                 abci_responses: ABCIResponses,
                 validator_updates: List[Validator]) -> State:
    """(execution.go:403 updateState)"""
    n_val_set = state.next_validators.copy()
    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        n_val_set.update_with_change_set(validator_updates)
        last_height_vals_changed = block.header.height + 1 + 1
    n_val_set.increment_proposer_priority(1)

    next_params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    version = state.version
    cpu = abci_responses.end_block.consensus_param_updates if abci_responses.end_block else None
    if cpu is not None:
        next_params = state.consensus_params.update(cpu)
        next_params.validate_basic()
        from ..types.block import Consensus

        version = Consensus(state.version.block, next_params.version.app_version)
        last_height_params_changed = block.header.height + 1

    return State(
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        version=version,
        last_block_height=block.header.height,
        last_block_id=block_id,
        last_block_time_ns=block.header.time_ns,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=next_params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=abci_responses.results_hash(),
        app_hash=b"",  # filled after Commit
    )


def fire_events(event_bus, block: Block, block_id: BlockID,
                abci_responses: ABCIResponses, validator_updates) -> None:
    """(execution.go:471 fireEvents)"""
    from ..types import events as tme

    event_bus.publish_event_new_block(block, block_id,
                                      abci_responses.begin_block, abci_responses.end_block)
    event_bus.publish_event_new_block_header(block.header,
                                             abci_responses.begin_block, abci_responses.end_block)
    for ev in block.evidence:
        event_bus.publish_event_new_evidence(ev, block.header.height)
    for i, tx in enumerate(block.data.txs):
        event_bus.publish_event_tx(block.header.height, i, tx, abci_responses.deliver_txs[i])
    if validator_updates:
        event_bus.publish_event_validator_set_updates(validator_updates)


def max_data_bytes_for(max_bytes: int, evidence_bytes: int, val_count: int) -> int:
    """(types/block.go MaxDataBytes)"""
    from ..types.block import MAX_HEADER_BYTES

    max_commit_bytes = 94 + (109 + 2) * val_count
    # block proto envelope overhead
    max_data = max_bytes - 11 - MAX_HEADER_BYTES - max_commit_bytes - evidence_bytes
    if max_data < 0:
        raise ValueError("negative MaxDataBytes")
    return max_data


def exec_commit_block(proxy_app: Client, block: Block, state_store: StateStore,
                      initial_height: int) -> bytes:
    """Replay helper (execution.go:530 ExecCommitBlock): exec + commit, return app hash."""
    exec_block_on_proxy_app(proxy_app, block, state_store, initial_height)
    res = proxy_app.commit()
    return res.data
