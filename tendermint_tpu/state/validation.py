"""Stateful block validation (reference state/validation.go:15 validateBlock).

LastCommit verification routes through the batched ValidatorSet.verify_commit
— HOT LOOP #2 in SURVEY.md §3.3 — one device call per block instead of N
scalar verifies.
"""

from __future__ import annotations

from ..types.block import Block


def validate_block(state, block: Block) -> None:
    block.validate_basic()

    if (block.header.version.app != state.version.app
            or block.header.version.block != state.version.block):
        raise ValueError(
            f"wrong Block.Header.Version. Expected {state.version}, got {block.header.version}")
    if block.header.chain_id != state.chain_id:
        raise ValueError(
            f"wrong Block.Header.ChainID. Expected {state.chain_id}, got {block.header.chain_id}")
    if state.last_block_height == 0 and block.header.height != state.initial_height:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.initial_height} for initial block, "
            f"got {block.header.height}")
    if state.last_block_height > 0 and block.header.height != state.last_block_height + 1:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.last_block_height + 1}, "
            f"got {block.header.height}")
    if block.header.last_block_id != state.last_block_id:
        raise ValueError(
            f"wrong Block.Header.LastBlockID. Expected {state.last_block_id}, "
            f"got {block.header.last_block_id}")

    if block.header.app_hash != state.app_hash:
        raise ValueError(
            f"wrong Block.Header.AppHash. Expected {state.app_hash.hex().upper()}, "
            f"got {block.header.app_hash.hex()}")
    hash_cp = state.consensus_params.hash()
    if block.header.consensus_hash != hash_cp:
        raise ValueError("wrong Block.Header.ConsensusHash")
    if block.header.last_results_hash != state.last_results_hash:
        raise ValueError("wrong Block.Header.LastResultsHash")
    if block.header.validators_hash != state.validators.hash():
        raise ValueError("wrong Block.Header.ValidatorsHash")
    if block.header.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong Block.Header.NextValidatorsHash")

    # LastCommit — the batched hot path.
    if block.header.height == state.initial_height:
        # size() covers both forms: CommitSig rows or signer bitmap
        if block.last_commit is not None and block.last_commit.size() != 0:
            raise ValueError("initial block can't have LastCommit signatures")
    else:
        state.last_validators.verify_commit(
            state.chain_id, state.last_block_id, block.header.height - 1, block.last_commit)

    # Proposer must be in the current validator set.
    if not state.validators.has_address(block.header.proposer_address):
        raise ValueError(
            f"block.Header.ProposerAddress {block.header.proposer_address.hex().upper()} "
            f"is not a validator")

    # Validate block time (state/validation.go:114-140).
    from .state import median_time

    if block.header.height > state.initial_height:
        if block.header.time_ns <= state.last_block_time_ns:
            raise ValueError(
                f"block time {block.header.time_ns} not greater than last block time "
                f"{state.last_block_time_ns}")
        expected = median_time(block.last_commit, state.last_validators)
        if block.header.time_ns != expected:
            raise ValueError(
                f"invalid block time. Expected {expected}, got {block.header.time_ns}")
    elif block.header.height == state.initial_height:
        if block.header.time_ns != state.last_block_time_ns:
            raise ValueError(
                f"block time {block.header.time_ns} is not equal to genesis time "
                f"{state.last_block_time_ns}")
    else:
        raise ValueError(
            f"block height {block.header.height} lower than initial height {state.initial_height}")
