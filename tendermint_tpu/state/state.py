"""state.State — the deterministic chain-tip value struct
(reference state/state.go:48) + MakeBlock and MedianTime.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from ..types import ConsensusParams, GenesisDoc, ValidatorSet
from ..types.basic import BlockID
from ..types.block import BLOCK_PROTOCOL, Block, Commit, Consensus, Header
from ..types.part_set import PartSet
from ..types.validator import Validator

# Version.Software analogue (reference version/version.go TMVersionDefault).
SOFTWARE_VERSION = "0.1.0-tpu"


@dataclass
class State:
    chain_id: str = ""
    initial_height: int = 1
    version: Consensus = field(default_factory=lambda: Consensus(BLOCK_PROTOCOL, 0))
    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time_ns: int = 0
    next_validators: Optional[ValidatorSet] = None
    validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0
    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            version=self.version,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time_ns=self.last_block_time_ns,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
            last_height_validators_changed=self.last_height_validators_changed,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.last_height_consensus_params_changed,
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    def make_block(self, height: int, txs: List[bytes], commit: Optional[Commit],
                   evidence: List, proposer_address: bytes) -> Tuple[Block, PartSet]:
        """(state/state.go:234)"""
        from ..types.block import Data

        if height == self.initial_height:
            timestamp = self.last_block_time_ns  # genesis time
        else:
            timestamp = median_time(commit, self.last_validators)
        header = Header(
            version=self.version,
            chain_id=self.chain_id,
            height=height,
            time_ns=timestamp,
            last_block_id=self.last_block_id,
            validators_hash=self.validators.hash(),
            next_validators_hash=self.next_validators.hash(),
            consensus_hash=self.consensus_params.hash(),
            app_hash=self.app_hash,
            last_results_hash=self.last_results_hash,
            proposer_address=proposer_address,
        )
        block = Block(header, Data(txs=list(txs)), list(evidence), commit)
        return block, block.make_part_set()


def median_time(commit: Commit, validators: ValidatorSet) -> int:
    """Voting-power-weighted median of commit vote timestamps
    (reference state/state.go:268 MedianTime)."""
    if hasattr(commit, "agg_sig"):
        # Aggregated commits carry the weighted median precomputed at
        # assembly time — the per-vote timestamps are not on the wire, and
        # the aggregate signature does NOT cover timestamp_ns (every
        # precommit signs zero-timestamp bytes, schemes.AGG_ZERO_TS_NS).
        # BFT time therefore weakens to proposer-assembled time bounded by
        # (a) deterministic monotonicity vs the previous block
        # (validation.validate_block) and (b) the subjective prevote-time
        # window each validator enforces against its own recorded precommit
        # times and local clock (consensus.state.check_aggregated_commit_time,
        # agg_commit_time_drift_s knob).
        return commit.timestamp_ns
    weighted = []
    total_power = 0
    for cs in commit.signatures:
        if cs.absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is None:
            continue
        total_power += val.voting_power
        weighted.append((cs.timestamp_ns, val.voting_power))
    weighted.sort()
    median = total_power // 2
    for ts, power in weighted:
        if median <= power:  # types/time/time.go:50 WeightedMedian
            return ts
        median -= power
    return 0


def state_from_genesis(genesis: GenesisDoc) -> State:
    """(reference state/state.go MakeGenesisState)"""
    genesis.validate_and_complete()
    from ..crypto import schemes

    schemes.register_chain(
        genesis.chain_id,
        (genesis.consensus_params or ConsensusParams()).signature)
    if genesis.validators:
        vals = [Validator(v.address, v.pub_key, v.power) for v in genesis.validators]
        val_set = ValidatorSet(vals)
        next_vals = val_set.copy_increment_proposer_priority(1)
    else:
        val_set = ValidatorSet()  # empty until InitChain supplies validators
        next_vals = ValidatorSet()
    return State(
        chain_id=genesis.chain_id,
        initial_height=genesis.initial_height,
        version=Consensus(BLOCK_PROTOCOL, (genesis.consensus_params or ConsensusParams()).version.app_version),
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time_ns=genesis.genesis_time_ns,
        next_validators=next_vals,
        validators=val_set,
        last_validators=ValidatorSet(),
        last_height_validators_changed=genesis.initial_height,
        consensus_params=genesis.consensus_params or ConsensusParams(),
        last_height_consensus_params_changed=genesis.initial_height,
        last_results_hash=b"",
        app_hash=genesis.app_hash,
    )
