"""Optimistic parallel block execution (the ROADMAP's "parallel execution
plane"; no direct reference analog — tendermint executes DeliverTx serially,
state/execution.go:259).

The serial path (:func:`.execution.exec_block_on_proxy_app`) is the SPEC:
this module must produce a byte-identical ``ABCIResponses`` list, app hash,
and event order for every block, or it doesn't run at all. The shape is
classic optimistic concurrency control, keyed off the ingest plane's
per-sender lanes:

1. **Partition** the block's txs into conflict groups by
   :func:`mempool.ingest.conflict_hint` — signed ``stx1`` envelopes group
   by sender pubkey, unsigned txs by parsed kvstore key, validator-update
   and unparseable txs into one serial barrier group. The hint is ONLY a
   scheduling guess; nothing below trusts it.
2. **Speculate** each group concurrently against a :class:`SpecView` — a
   copy-on-write overlay over committed app state that records every
   logical key a tx reads or writes plus a replayable op log. Speculation
   never mutates the app, so a failed run is discarded for free.
3. **Validate** after the join: compute the conflict closure — the least
   fixpoint of (keys touched by ≥ 2 groups) ∪ (keys touched by any
   conflicted tx). Txs outside the closure touched only keys their own
   group owns, so their speculative reads — and therefore their responses
   — are exactly what serial execution would have produced.
4. **Apply + re-execute** under the app mutex: replay non-conflicted op
   logs in block order, then re-run only the conflicted txs through the
   real ``deliver_tx`` in block order. Closure keys are touched *only* by
   conflicted txs, so the re-run sees precisely the serial state.

Apps opt in by setting ``parallel_exec_supported`` and implementing
``spec_read`` / ``deliver_tx_on_view`` / ``apply_spec_ops``
(abci/application.py documents the contract; abci/example/kvstore.py is
the model). Anything else — remote apps, tiny blocks, a speculation
error — falls back to the serial spec, counted per reason on
``state_parallel_exec_fallbacks_total``.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..abci.client import Client, LocalClient
from ..libs.faults import faults
from ..mempool.ingest import conflict_hint
from ..types.block import Block
from .store import ABCIResponses, StateStore

logger = logging.getLogger("tmtpu.state.parallel")

#: logical key spaces a view tracks; (space, key) tuples are the unit of
#: conflict detection. "vup" is the ordered validator-update stream: every
#: emitter touches the SAME ("vup", "") key, so validator updates from
#: different groups can never silently interleave — the closure pulls all
#: of them into the serial re-execution together (all-or-nothing).
Key = Tuple[str, str]


class TxLog:
    """Read/write record of one speculated tx."""

    __slots__ = ("idx", "keys", "ops", "response")

    def __init__(self, idx: int):
        self.idx = idx
        self.keys: Set[Key] = set()
        self.ops: List[tuple] = []
        self.response: Optional[abci.ResponseDeliverTx] = None


class SpecView:
    """Copy-on-write overlay for one conflict group's speculation.

    Reads hit the overlay first (earlier txs of the SAME group, in block
    order) and fall back to the app's committed state via ``spec_read``.
    Ops are app-defined tuples replayed verbatim by ``apply_spec_ops`` —
    the view only guarantees they are logged per tx, in execution order.
    """

    __slots__ = ("_app", "_overlay", "logs", "_log")

    def __init__(self, app):
        self._app = app
        self._overlay: Dict[Key, object] = {}
        self.logs: List[TxLog] = []
        self._log: Optional[TxLog] = None

    def begin_tx(self, idx: int) -> None:
        self._log = TxLog(idx)
        self.logs.append(self._log)

    def read(self, space: str, key: str):
        k = (space, key)
        self._log.keys.add(k)
        if k in self._overlay:
            return self._overlay[k]
        return self._app.spec_read(space, key)

    def write(self, space: str, key: str, value, extra=None) -> None:
        k = (space, key)
        self._log.keys.add(k)
        self._overlay[k] = value
        self._log.ops.append(("set", space, key, value, extra))

    def emit(self, space: str, value) -> None:
        """Ordered append to a shared per-space stream: touches the
        stream's single shared key, so cross-group emitters always
        conflict (and thus re-execute in block order)."""
        self._log.keys.add((space, ""))
        self._log.ops.append(("emit", space, value))

    def add(self, counter: str, n: int = 1) -> None:
        """Commutative counter bump — keyless, never conflicts."""
        self._log.ops.append(("add", counter, n))


def conflict_groups(txs: List[bytes]) -> List[List[int]]:
    """Partition tx indices by conflict hint, preserving block order both
    across groups (first appearance) and within each group. The
    ``exec.conflict`` chaos site seeded-perturbs assignments into
    deliberately wrong lanes — correctness must then come from
    validation + re-execution, which is exactly what the site tests."""
    groups: Dict[Tuple[str, str], List[int]] = {}
    chaos = faults.armed("exec.conflict")
    for i, tx in enumerate(txs):
        hint = conflict_hint(tx)
        if chaos and faults.fire("exec.conflict"):
            hint = ("chaos", str(i % 2))
        groups.setdefault(hint, []).append(i)
    return list(groups.values())


def conflict_closure(logs: List[TxLog], group_of: Dict[int, int]
                     ) -> Tuple[Set[int], Set[Key]]:
    """Least fixpoint of conflicted txs/keys.

    Seed: keys touched by two or more groups. Grow: every tx touching a
    conflicted key is conflicted, and every key a conflicted tx touches
    becomes conflicted. At the fixpoint, non-conflicted txs touch only
    keys owned exclusively by their group's non-conflicted txs — the
    property that makes their speculative responses serial-identical."""
    key_groups: Dict[Key, Set[int]] = {}
    for log in logs:
        gi = group_of[log.idx]
        for k in log.keys:
            key_groups.setdefault(k, set()).add(gi)
    ck: Set[Key] = {k for k, gs in key_groups.items() if len(gs) > 1}
    ct: Set[int] = set()
    changed = True
    while changed:
        changed = False
        for log in logs:
            if log.idx in ct or not log.keys:
                continue
            if log.keys & ck:
                ct.add(log.idx)
                if not log.keys <= ck:
                    ck |= log.keys
                changed = True
    return ct, ck


class ParallelExecutor:
    """Optimistic executor bound to one BlockExecutor's proxy connection.

    ``try_exec_block`` returns None when the parallel path can't run
    (remote app, app without the view protocol, tiny block) or aborts
    (speculation raised) — the caller then takes the serial spec path.
    """

    def __init__(self, workers: int = 4, min_parallel_txs: int = 2,
                 metrics=None):
        import os

        # more spec threads than cores only adds contention: on a 1-core
        # host speculation degrades to in-line (still batched apply)
        self.workers = max(1, min(int(workers), os.cpu_count() or 1))
        self.min_parallel_txs = max(0, int(min_parallel_txs))
        self.metrics = metrics
        # last-block stats, for tests and the bench payload
        self.last_groups = 0
        self.last_conflicted = 0

    def _fallback(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.parallel_exec_fallbacks.labels(reason).inc()

    def try_exec_block(self, proxy_app: Client, block: Block,
                       state_store: StateStore,
                       initial_height: int) -> Optional[ABCIResponses]:
        from .execution import ev_to_abci, get_begin_block_validator_info

        if not isinstance(proxy_app, LocalClient):
            self._fallback("remote-app")
            return None
        app, mtx = proxy_app._app, proxy_app._mtx
        if not getattr(app, "parallel_exec_supported", False):
            self._fallback("app-unsupported")
            return None
        txs = block.data.txs
        if len(txs) < self.min_parallel_txs:
            self._fallback("small-block")
            return None

        commit_info = get_begin_block_validator_info(
            block, state_store, initial_height)
        byz_vals = [ev_to_abci(ev) for ev in block.evidence]
        begin = proxy_app.begin_block(abci.RequestBeginBlock(
            hash=block.hash() or b"", header=block.header,
            last_commit_info=commit_info, byzantine_validators=byz_vals))

        groups = conflict_groups(txs)
        views = [SpecView(app) for _ in groups]

        def speculate(gi: int) -> None:
            view = views[gi]
            for idx in groups[gi]:
                view.begin_tx(idx)
                resp = app.deliver_tx_on_view(txs[idx], view)
                view.logs[-1].response = resp

        # Speculation runs WITHOUT the app mutex: views never mutate the
        # app, and the only concurrent callers (mempool CheckTx, RPC
        # Query on their own connection locks) are read-only by the ABCI
        # contract. A raise here aborts cleanly to the serial path.
        try:
            if len(groups) > 1 and self.workers > 1:
                with ThreadPoolExecutor(
                        max_workers=min(self.workers, len(groups)),
                        thread_name_prefix="spec-exec") as pool:
                    for _ in pool.map(speculate, range(len(groups))):
                        pass
            else:
                for gi in range(len(groups)):
                    speculate(gi)
        except Exception:
            logger.exception("speculative execution aborted; "
                             "falling back to serial")
            self._fallback("spec-error")
            return None

        group_of = {idx: gi for gi, idxs in enumerate(groups)
                    for idx in idxs}
        logs = sorted((log for v in views for log in v.logs),
                      key=lambda l: l.idx)
        ct, _ck = conflict_closure(logs, group_of)

        responses: List[Optional[abci.ResponseDeliverTx]] = [None] * len(txs)
        # Apply under the app mutex: non-conflicted op logs replay in
        # block order (their key sets are disjoint from everything that
        # re-executes, so the interleaving is immaterial), then the
        # conflicted txs re-run through the REAL deliver_tx in block
        # order against exactly the serial state for their keys.
        with mtx:
            for log in logs:
                if log.idx not in ct:
                    app.apply_spec_ops(log.ops)
                    responses[log.idx] = log.response
            for idx in sorted(ct):
                responses[idx] = app.deliver_tx(
                    abci.RequestDeliverTx(tx=txs[idx]))

        invalid = sum(1 for r in responses if not r.is_ok())
        if invalid:
            logger.debug("executed block height=%d valid_txs=%d invalid_txs=%d",
                         block.header.height, len(responses) - invalid, invalid)
        end = proxy_app.end_block(
            abci.RequestEndBlock(height=block.header.height))

        self.last_groups = len(groups)
        self.last_conflicted = len(ct)
        if self.metrics is not None:
            self.metrics.parallel_exec_blocks.inc()
            if ct:
                self.metrics.parallel_exec_conflict_txs.inc(len(ct))
        # ORDERING CONTRACT (see ABCIResponses): deliver_txs[i] is the
        # response to block.data.txs[i]; event publication indexes into
        # this list by block position. The index-addressed assembly above
        # preserves it by construction; this assert locks it down.
        assert all(r is not None for r in responses), \
            "parallel execution left a response hole"
        return ABCIResponses(deliver_txs=responses, end_block=end,
                             begin_block=begin)
