"""tendermint_tpu — a TPU-native BFT state-machine-replication framework.

A from-scratch re-design of the capabilities of Tendermint Core v0.34.x
(reference: /root/reference) built JAX/XLA-first: the signature-verification
hot path (votes, commits, light-client headers) runs as a batched,
shardable kernel on TPU, behind the same pluggable crypto seam the
reference exposes (reference crypto/crypto.go:22-28).
"""

__version__ = "0.1.0"
