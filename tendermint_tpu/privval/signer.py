"""Remote signer protocol (reference privval/signer_client.go,
signer_listener_endpoint.go, signer_dialer_endpoint.go, signer_server.go —
the tmkms integration surface).

Topology matches the reference: the NODE listens on
``priv_validator_laddr``; the SIGNER process dials in and then serves
signing requests over that single connection. Messages are
length-delimited protobuf (proto/tendermint/privval/types.proto oneof):

    1 PubKeyRequest{chain_id}        2 PubKeyResponse{pub_key, error}
    3 SignVoteRequest{vote, chain_id}     4 SignedVoteResponse{vote, error}
    5 SignProposalRequest{proposal, ...}  6 SignedProposalResponse{...}
    7 PingRequest                    8 PingResponse

The TCP link is wrapped in SecretConnection with ed25519 peer
authentication, as the reference wraps tcp:// privval connections
(privval/socket_listeners.go:66 TCPListener → secret conn); either side
may additionally pin the peer's expected static key.

Blocking sockets on background threads, mirroring the reference's blocking
call discipline: consensus' synchronous sign_vote/sign_proposal calls block
until the signer answers (or time out).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Optional, Tuple

from ..crypto import Ed25519PrivKey, Ed25519PubKey, PrivKey, PubKey
from ..libs import protowire as pw
from ..p2p.conn.secret_connection import SyncSecretConnection
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.vote import Vote

logger = logging.getLogger("tmtpu.privval.signer")

DEFAULT_TIMEOUT = 5.0
# Votes/proposals are tiny; anything beyond this is a broken or hostile peer.
MAX_PRIVVAL_MSG = 64 * 1024


class RemoteSignerError(Exception):
    pass


# -- wire ---------------------------------------------------------------------

def _frame(field: int, body: bytes) -> bytes:
    w = pw.Writer()
    w.message(field, body)
    return pw.length_delimited(w.finish())


def _recv_msg(conn: SyncSecretConnection) -> Tuple[int, bytes]:
    framed = conn.read_msg(max_size=MAX_PRIVVAL_MSG)
    ln, pos = pw.decode_varint(framed, 0)
    for fn, _wt, v in pw.iter_fields(framed[pos:pos + ln]):
        return fn, v
    raise RemoteSignerError("empty privval message")


def _err_body(msg: str) -> bytes:
    w = pw.Writer()
    w.varint(1, 1)
    w.string(2, msg)
    return w.finish()


# -- signer side (dials the node; privval/signer_server.go) -------------------

class SignerServer:
    """Runs next to the key: dials the node and serves its FilePV.

    ``conn_key`` is the signer's long-lived connection identity for the
    SecretConnection handshake (generated if absent); ``expected_node_key``
    optionally pins the node's static ed25519 key.
    """

    def __init__(self, pv: PrivValidator, chain_id: str, addr: Tuple[str, int],
                 conn_key: Optional[PrivKey] = None,
                 expected_node_key: Optional[bytes] = None):
        self.pv = pv
        self.chain_id = chain_id
        self.addr = addr
        self.conn_key = conn_key or Ed25519PrivKey.generate()
        self.expected_node_key = expected_node_key
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="signer-server")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _run(self) -> None:
        # catch broadly: handshake failures, AEAD InvalidTag, oversized
        # frames etc. must redial, not silently kill the signer thread
        while not self._stopped.is_set():
            try:
                self._sock = socket.create_connection(self.addr, timeout=5.0)
                # keep the 5s timeout through the handshake so a mute or
                # half-open peer can't wedge the thread; block indefinitely
                # only once serving (requests arrive at the node's pace)
                conn = SyncSecretConnection.make(
                    self._sock, self.conn_key,
                    expected_remote_key=self.expected_node_key)
                self._sock.settimeout(None)
                logger.info("signer connected to %s:%d", *self.addr)
                self._serve(conn)
            except Exception as e:
                if self._stopped.is_set():
                    return
                logger.warning("signer connection lost (%s); redialing", e)
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                self._stopped.wait(1.0)

    def _serve(self, conn: SyncSecretConnection) -> None:
        while not self._stopped.is_set():
            fn, body = _recv_msg(conn)
            conn.write(self._handle(fn, body))

    def _handle(self, fn: int, body: bytes) -> bytes:
        fields = pw.fields_dict(body) if body else {}
        if fn == 1:  # PubKeyRequest
            pk = pw.Writer()
            pk.bytes(1, self.pv.get_pub_key().bytes())
            resp = pw.Writer()
            resp.message(1, pk.finish())
            return _frame(2, resp.finish())
        if fn == 3:  # SignVoteRequest
            try:
                vote = Vote.decode(fields[1][0])
                chain_id = fields.get(2, [b""])[0].decode() or self.chain_id
                self.pv.sign_vote(chain_id, vote)
                resp = pw.Writer()
                resp.message(1, vote.encode())
                return _frame(4, resp.finish())
            except Exception as e:
                resp = pw.Writer()
                resp.message(2, _err_body(str(e)))
                return _frame(4, resp.finish())
        if fn == 5:  # SignProposalRequest
            try:
                proposal = Proposal.decode(fields[1][0])
                chain_id = fields.get(2, [b""])[0].decode() or self.chain_id
                self.pv.sign_proposal(chain_id, proposal)
                resp = pw.Writer()
                resp.message(1, proposal.encode())
                return _frame(6, resp.finish())
            except Exception as e:
                resp = pw.Writer()
                resp.message(2, _err_body(str(e)))
                return _frame(6, resp.finish())
        if fn == 7:  # PingRequest
            return _frame(8, b"")
        resp = pw.Writer()
        resp.message(2, _err_body(f"unknown request {fn}"))
        return _frame(fn + 1, resp.finish())


# -- node side (listens; privval/signer_listener_endpoint.go + client) --------

class SignerListenerEndpoint:
    """Accepts the signer's inbound connection on priv_validator_laddr.

    ``conn_key`` is the node's connection identity (normally the node key);
    ``expected_signer_key`` optionally pins the signer's static key so only
    the authorized signer process can serve signatures.
    """

    def __init__(self, host: str, port: int, timeout: float = DEFAULT_TIMEOUT,
                 conn_key: Optional[PrivKey] = None,
                 expected_signer_key: Optional[bytes] = None):
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self.conn_key = conn_key or Ed25519PrivKey.generate()
        self.expected_signer_key = expected_signer_key
        if expected_signer_key is None:
            # without a pinned key, whichever process dials first holds the
            # signer slot and can stall consensus signing with well-formed
            # errors — the handshake alone cannot tell the real signer apart
            logger.warning(
                "priv_validator_laddr listener on %s:%d has NO pinned signer "
                "key: any dialer that completes the SecretConnection "
                "handshake will be trusted as the signer; configure "
                "priv_validator_signer_key for production", self.host,
                self.port)
        self._conn: Optional[SyncSecretConnection] = None
        self._connected = threading.Event()
        self._lock = threading.Lock()
        self.timeout = timeout
        self._stopped = False
        self._accept_thread: Optional[threading.Thread] = None

    def _accept_loop(self) -> None:
        """Keep accepting: a failed handshake (port scanner, wrong pinned
        key) drops that conn and waits for the next — it must never wedge
        the endpoint (the reference listener likewise keeps accepting).
        Each handshake runs on its own thread so a stalling dialer cannot
        starve the real signer's reconnect."""
        # finite accept timeout: close(2) does not wake a thread blocked in
        # accept(2), so the loop polls _stopped to actually exit (and free
        # the bound port) after close()
        self._listener.settimeout(1.0)
        while not self._stopped:
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            threading.Thread(target=self._handshake_one, args=(sock, addr),
                             daemon=True, name="signer-handshake").start()

    def _handshake_one(self, sock: socket.socket, addr) -> None:
        try:
            sock.settimeout(self.timeout)
            conn = SyncSecretConnection.make(
                sock, self.conn_key,
                expected_remote_key=self.expected_signer_key)
        except Exception as e:
            logger.warning("rejecting signer connection from %s: %s", addr, e)
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            if self._conn is not None:
                # never evict a live authenticated signer — an unauthorized
                # dialer completing a handshake must not hijack the link;
                # a dead conn is cleared by request()'s failure teardown
                logger.warning("signer already connected; dropping conn "
                               "from %s", addr)
                conn.close()
                return
            self._conn = conn
        self._connected.set()
        logger.info("remote signer connected from %s", addr)

    def wait_for_signer(self, timeout: float = 30.0) -> None:
        if self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True, name="signer-accept")
            self._accept_thread.start()
        if not self._connected.wait(timeout):
            raise RemoteSignerError("no signer connected within deadline")

    def request(self, framed: bytes) -> Tuple[int, bytes]:
        with self._lock:  # one in-flight request (reference serializes too)
            if self._conn is None:
                raise RemoteSignerError("no signer connected")
            try:
                self._conn.write(framed)
                return _recv_msg(self._conn)
            except Exception as e:
                # a timeout or frame error desyncs the AEAD stream — tear the
                # conn down; the signer redials and the accept loop re-arms
                self._conn.close()
                self._conn = None
                self._connected.clear()
                raise RemoteSignerError(f"signer request failed: {e}") from e

    def close(self) -> None:
        self._stopped = True
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
        try:
            self._listener.close()
        except OSError:
            pass


class SignerClient(PrivValidator):
    """PrivValidator over a SignerListenerEndpoint
    (privval/signer_client.go)."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._pub: Optional[PubKey] = None

    def get_pub_key(self) -> PubKey:
        if self._pub is None:
            w = pw.Writer()
            w.string(1, self.chain_id)
            fn, body = self.endpoint.request(_frame(1, w.finish()))
            if fn != 2:
                raise RemoteSignerError(f"unexpected response {fn}")
            fields = pw.fields_dict(body)
            if 2 in fields:
                raise RemoteSignerError(_err_text(fields[2][0]))
            pk_fields = pw.fields_dict(fields[1][0])
            self._pub = Ed25519PubKey(pk_fields[1][0])
        return self._pub

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        w = pw.Writer()
        w.message(1, vote.encode())
        w.string(2, chain_id)
        fn, body = self.endpoint.request(_frame(3, w.finish()))
        if fn != 4:
            raise RemoteSignerError(f"unexpected response {fn}")
        fields = pw.fields_dict(body)
        if 2 in fields:
            raise RemoteSignerError(_err_text(fields[2][0]))
        signed = Vote.decode(fields[1][0])
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        w = pw.Writer()
        w.message(1, proposal.encode())
        w.string(2, chain_id)
        fn, body = self.endpoint.request(_frame(5, w.finish()))
        if fn != 6:
            raise RemoteSignerError(f"unexpected response {fn}")
        fields = pw.fields_dict(body)
        if 2 in fields:
            raise RemoteSignerError(_err_text(fields[2][0]))
        signed = Proposal.decode(fields[1][0])
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns

    def ping(self) -> bool:
        try:
            fn, _ = self.endpoint.request(_frame(7, b""))
            return fn == 8
        except Exception:
            return False


def _err_text(body: bytes) -> str:
    fields = pw.fields_dict(body)
    raw = fields.get(2, [b""])[0]
    return raw.decode() if isinstance(raw, bytes) else str(raw)
