"""FilePV: file-backed validator key with double-sign protection
(reference privval/file.go:148).

Persisted last-sign-state (H/R/Step + sign-bytes) forbids re-signing a
different value at the same HRS; the only allowed re-sign is the same vote
differing ONLY by timestamp (file.go:400 checkVotesOnlyDifferByTimestamp) —
the remote-signer reconnect case.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional, Tuple

from .. import crypto
from ..libs import protowire as pw
from ..libs.fail import fail_point
from ..libs.faults import faults
from ..types.basic import SignedMsgType
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.vote import Vote

logger = logging.getLogger("tmtpu.privval")

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(v: Vote) -> int:
    if v.type == SignedMsgType.PREVOTE:
        return STEP_PREVOTE
    if v.type == SignedMsgType.PRECOMMIT:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type: {v.type}")


class DoubleSignError(Exception):
    pass


class CorruptSignStateError(Exception):
    """The last-sign-state file exists but cannot be decoded. Fatal at
    startup BY DESIGN: silently resetting to height 0 would let this
    validator re-sign heights it already signed — the double-sign hazard
    the file exists to prevent. The operator must restore the file from
    backup (or, only if certain this key never signed, remove it)."""

    def __init__(self, path: str, cause: Exception):
        super().__init__(
            f"priv validator state file {path!r} is corrupt ({cause}); "
            f"refusing to start — silently resetting the sign state would "
            f"allow double-signing. Restore {path!r} from backup, or remove "
            f"it ONLY if this validator key has never signed.")
        self.path = path


@dataclass
class LastSignState:
    """(file.go:75 FilePVLastSignState)"""

    height: int = 0
    round: int = 0
    step: int = STEP_NONE
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if HRS matches exactly and a signature exists
        (file.go:92 CheckHRS). Raises on regression."""
        if self.height > height:
            raise DoubleSignError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}. Got {round_}, last round {self.round}")
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}")
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no SignBytes found")
                    if not self.signature:
                        raise RuntimeError("pv: Signature is nil but SignBytes is not!")
                    return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        data = json.dumps({
            "height": self.height, "round": self.round, "step": self.step,
            "signature": self.signature.hex(), "signbytes": self.sign_bytes.hex(),
        }, indent=2)
        _atomic_write(self.file_path, data, tear_site="privval.torn_state")

    @staticmethod
    def load(path: str) -> "LastSignState":
        """Decode the persisted sign state; a file that exists but cannot
        be decoded raises CorruptSignStateError naming the file (never a
        bare decode error, never a silent height-0 reset)."""
        with open(path, "rb") as f:
            raw = f.read()
        try:
            d = json.loads(raw.decode())
            return LastSignState(
                height=int(d.get("height", 0)), round=int(d.get("round", 0)),
                step=int(d.get("step", STEP_NONE)),
                signature=bytes.fromhex(d.get("signature", "")),
                sign_bytes=bytes.fromhex(d.get("signbytes", "")),
                file_path=path,
            )
        except (ValueError, UnicodeDecodeError, AttributeError, TypeError) as e:
            raise CorruptSignStateError(path, e) from e


def _atomic_write(path: str, data: str, tear_site: Optional[str] = None) -> None:
    """(libs/tempfile atomic write) — temp write + fsync + rename + DIR
    fsync: os.replace puts the new name in the directory, but the rename
    itself is only durable once the directory inode is synced; without it
    a crash right after replace can resurrect the OLD file (or no file).
    ``tear_site`` routes the payload through the torn-write fault seam at
    the byte-emit point (a fired site persists a strictly partial file —
    what an fsync-less crash mid-write leaves)."""
    d = os.path.dirname(path) or "."
    payload = data.encode()
    if tear_site is not None:
        payload = faults.tear(tear_site, payload)
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _fsync_dir(d: str) -> None:
    try:
        dfd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # platform without directory opens (e.g. Windows)
    try:
        os.fsync(dfd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is best-effort there
    finally:
        os.close(dfd)


class FilePV(PrivValidator):
    def __init__(self, priv_key: crypto.PrivKey, key_file_path: str = "",
                 state_file_path: str = ""):
        self.priv_key = priv_key
        self.key_file_path = key_file_path
        self.last_sign_state = LastSignState(file_path=state_file_path)

    # -- persistence -------------------------------------------------------

    @staticmethod
    def generate(key_file_path: str = "", state_file_path: str = "",
                 seed: Optional[bytes] = None,
                 key_type: str = crypto.ED25519_TYPE) -> "FilePV":
        if key_type == crypto.BLS12381_TYPE:
            priv = crypto.Bls12381PrivKey.generate(seed)
        else:
            priv = crypto.Ed25519PrivKey.generate(seed)
        return FilePV(priv, key_file_path, state_file_path)

    def save(self) -> None:
        if self.key_file_path:
            pub = self.priv_key.pub_key()
            data = json.dumps({
                "address": pub.address().hex().upper(),
                "pub_key": {"type": pub.type_name, "value": pub.bytes().hex()},
                "priv_key": {"type": self.priv_key.type_name,
                             "value": self.priv_key.bytes().hex()},
            }, indent=2)
            _atomic_write(self.key_file_path, data)
        self.last_sign_state.save()

    @staticmethod
    def load(key_file_path: str, state_file_path: str) -> "FilePV":
        with open(key_file_path) as f:
            d = json.load(f)
        key_bytes = bytes.fromhex(d["priv_key"]["value"])
        if d["priv_key"].get("type") == crypto.BLS12381_TYPE:
            priv: crypto.PrivKey = crypto.Bls12381PrivKey(key_bytes)
        else:
            priv = crypto.Ed25519PrivKey(key_bytes)
        pv = FilePV(priv, key_file_path, state_file_path)
        if os.path.exists(state_file_path):
            # a corrupt file raises CorruptSignStateError — startup must
            # fail loudly, never silently reset (the double-sign hazard)
            pv.last_sign_state = LastSignState.load(state_file_path)
        else:
            # the key exists but its sign state doesn't: legitimate only on
            # a brand-new validator — if this node ever signed, starting at
            # height 0 re-arms every height for re-signing. The node layer
            # re-checks this against the block store and escalates.
            logger.warning(
                "priv validator state file %s is absent; initializing sign "
                "state at height 0 — if this validator has signed before, "
                "restore the file instead of proceeding", state_file_path)
            pv.last_sign_state = LastSignState(file_path=state_file_path)
        return pv

    # -- PrivValidator -----------------------------------------------------

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """(file.go:303 signVote)"""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            # Only timestamp may differ (file.go:330-343)
            if lss.sign_bytes == sign_bytes:
                vote.signature = lss.signature
                return
            ts, ok = _votes_only_differ_by_timestamp(lss.sign_bytes, sign_bytes)
            if ok:
                vote.timestamp_ns = ts
                vote.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """(file.go:356 signProposal)"""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if lss.sign_bytes == sign_bytes:
                proposal.signature = lss.signature
                return
            ts, ok = _proposals_only_differ_by_timestamp(lss.sign_bytes, sign_bytes)
            if ok:
                proposal.timestamp_ns = ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        proposal.signature = sig

    def _save_signed(self, height: int, round_: int, step: int,
                     sign_bytes: bytes, sig: bytes) -> None:
        # durability boundary (crashmatrix): the signature exists but the
        # sign state doesn't yet — a kill here must recover without the
        # restarted validator equivocating (the unsent signature dies with
        # the process; the state file still holds the previous HRS)
        fail_point("privval.between_sign_and_save")
        lss = self.last_sign_state
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature = sig
        lss.sign_bytes = sign_bytes
        lss.save()


def _strip_timestamp_vote(sign_bytes: bytes) -> Tuple[bytes, int]:
    """Canonical vote sign-bytes with the timestamp field (5) zeroed; returns
    (stripped encoding, timestamp_ns) — file.go:400 semantics."""
    body, _ = pw.read_length_delimited(sign_bytes)
    w = pw.Writer()
    ts = 0
    for fn, wt, v in pw.iter_fields(body):
        if fn == 5 and wt == pw.WIRE_BYTES:
            ts = pw.parse_timestamp(v)
            continue
        _rewrite_field(w, fn, wt, v)
    return w.finish(), ts


def _strip_timestamp_proposal(sign_bytes: bytes) -> Tuple[bytes, int]:
    body, _ = pw.read_length_delimited(sign_bytes)
    w = pw.Writer()
    ts = 0
    for fn, wt, v in pw.iter_fields(body):
        if fn == 6 and wt == pw.WIRE_BYTES:
            ts = pw.parse_timestamp(v)
            continue
        _rewrite_field(w, fn, wt, v)
    return w.finish(), ts


def _rewrite_field(w: pw.Writer, fn: int, wt: int, v) -> None:
    if wt == pw.WIRE_VARINT:
        w._buf += pw.tag(fn, wt) + pw.encode_varint(v)
    elif wt == pw.WIRE_FIXED64:
        w._buf += pw.tag(fn, wt) + v.to_bytes(8, "little")
    elif wt == pw.WIRE_BYTES:
        w._buf += pw.tag(fn, wt) + pw.encode_varint(len(v)) + v
    else:
        raise ValueError(f"unsupported wire type {wt}")


def _votes_only_differ_by_timestamp(last: bytes, new: bytes) -> Tuple[int, bool]:
    last_stripped, last_ts = _strip_timestamp_vote(last)
    new_stripped, _ = _strip_timestamp_vote(new)
    return last_ts, last_stripped == new_stripped


def _proposals_only_differ_by_timestamp(last: bytes, new: bytes) -> Tuple[int, bool]:
    last_stripped, last_ts = _strip_timestamp_proposal(last)
    new_stripped, _ = _strip_timestamp_proposal(new)
    return last_ts, last_stripped == new_stripped


def load_or_gen_file_pv(key_file_path: str, state_file_path: str) -> FilePV:
    """(file.go LoadOrGenFilePV)"""
    if os.path.exists(key_file_path):
        return FilePV.load(key_file_path, state_file_path)
    pv = FilePV.generate(key_file_path, state_file_path)
    pv.save()
    return pv
