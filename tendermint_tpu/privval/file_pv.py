"""FilePV: file-backed validator key with double-sign protection
(reference privval/file.go:148).

Persisted last-sign-state (H/R/Step + sign-bytes) forbids re-signing a
different value at the same HRS; the only allowed re-sign is the same vote
differing ONLY by timestamp (file.go:400 checkVotesOnlyDifferByTimestamp) —
the remote-signer reconnect case.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional, Tuple

from .. import crypto
from ..libs import protowire as pw
from ..types.basic import SignedMsgType
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.vote import Vote

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(v: Vote) -> int:
    if v.type == SignedMsgType.PREVOTE:
        return STEP_PREVOTE
    if v.type == SignedMsgType.PRECOMMIT:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type: {v.type}")


class DoubleSignError(Exception):
    pass


@dataclass
class LastSignState:
    """(file.go:75 FilePVLastSignState)"""

    height: int = 0
    round: int = 0
    step: int = STEP_NONE
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if HRS matches exactly and a signature exists
        (file.go:92 CheckHRS). Raises on regression."""
        if self.height > height:
            raise DoubleSignError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}. Got {round_}, last round {self.round}")
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}")
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no SignBytes found")
                    if not self.signature:
                        raise RuntimeError("pv: Signature is nil but SignBytes is not!")
                    return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        data = json.dumps({
            "height": self.height, "round": self.round, "step": self.step,
            "signature": self.signature.hex(), "signbytes": self.sign_bytes.hex(),
        }, indent=2)
        _atomic_write(self.file_path, data)

    @staticmethod
    def load(path: str) -> "LastSignState":
        with open(path) as f:
            d = json.load(f)
        return LastSignState(
            height=d.get("height", 0), round=d.get("round", 0),
            step=d.get("step", STEP_NONE),
            signature=bytes.fromhex(d.get("signature", "")),
            sign_bytes=bytes.fromhex(d.get("signbytes", "")),
            file_path=path,
        )


def _atomic_write(path: str, data: str) -> None:
    """(libs/tempfile atomic write)"""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class FilePV(PrivValidator):
    def __init__(self, priv_key: crypto.PrivKey, key_file_path: str = "",
                 state_file_path: str = ""):
        self.priv_key = priv_key
        self.key_file_path = key_file_path
        self.last_sign_state = LastSignState(file_path=state_file_path)

    # -- persistence -------------------------------------------------------

    @staticmethod
    def generate(key_file_path: str = "", state_file_path: str = "",
                 seed: Optional[bytes] = None) -> "FilePV":
        pv = FilePV(crypto.Ed25519PrivKey.generate(seed), key_file_path, state_file_path)
        return pv

    def save(self) -> None:
        if self.key_file_path:
            pub = self.priv_key.pub_key()
            data = json.dumps({
                "address": pub.address().hex().upper(),
                "pub_key": {"type": pub.type_name, "value": pub.bytes().hex()},
                "priv_key": {"type": self.priv_key.type_name,
                             "value": self.priv_key.bytes().hex()},
            }, indent=2)
            _atomic_write(self.key_file_path, data)
        self.last_sign_state.save()

    @staticmethod
    def load(key_file_path: str, state_file_path: str) -> "FilePV":
        with open(key_file_path) as f:
            d = json.load(f)
        priv = crypto.Ed25519PrivKey(bytes.fromhex(d["priv_key"]["value"]))
        pv = FilePV(priv, key_file_path, state_file_path)
        if os.path.exists(state_file_path):
            pv.last_sign_state = LastSignState.load(state_file_path)
        else:
            pv.last_sign_state = LastSignState(file_path=state_file_path)
        return pv

    # -- PrivValidator -----------------------------------------------------

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """(file.go:303 signVote)"""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            # Only timestamp may differ (file.go:330-343)
            if lss.sign_bytes == sign_bytes:
                vote.signature = lss.signature
                return
            ts, ok = _votes_only_differ_by_timestamp(lss.sign_bytes, sign_bytes)
            if ok:
                vote.timestamp_ns = ts
                vote.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """(file.go:356 signProposal)"""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if lss.sign_bytes == sign_bytes:
                proposal.signature = lss.signature
                return
            ts, ok = _proposals_only_differ_by_timestamp(lss.sign_bytes, sign_bytes)
            if ok:
                proposal.timestamp_ns = ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        proposal.signature = sig

    def _save_signed(self, height: int, round_: int, step: int,
                     sign_bytes: bytes, sig: bytes) -> None:
        lss = self.last_sign_state
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature = sig
        lss.sign_bytes = sign_bytes
        lss.save()


def _strip_timestamp_vote(sign_bytes: bytes) -> Tuple[bytes, int]:
    """Canonical vote sign-bytes with the timestamp field (5) zeroed; returns
    (stripped encoding, timestamp_ns) — file.go:400 semantics."""
    body, _ = pw.read_length_delimited(sign_bytes)
    w = pw.Writer()
    ts = 0
    for fn, wt, v in pw.iter_fields(body):
        if fn == 5 and wt == pw.WIRE_BYTES:
            ts = pw.parse_timestamp(v)
            continue
        _rewrite_field(w, fn, wt, v)
    return w.finish(), ts


def _strip_timestamp_proposal(sign_bytes: bytes) -> Tuple[bytes, int]:
    body, _ = pw.read_length_delimited(sign_bytes)
    w = pw.Writer()
    ts = 0
    for fn, wt, v in pw.iter_fields(body):
        if fn == 6 and wt == pw.WIRE_BYTES:
            ts = pw.parse_timestamp(v)
            continue
        _rewrite_field(w, fn, wt, v)
    return w.finish(), ts


def _rewrite_field(w: pw.Writer, fn: int, wt: int, v) -> None:
    if wt == pw.WIRE_VARINT:
        w._buf += pw.tag(fn, wt) + pw.encode_varint(v)
    elif wt == pw.WIRE_FIXED64:
        w._buf += pw.tag(fn, wt) + v.to_bytes(8, "little")
    elif wt == pw.WIRE_BYTES:
        w._buf += pw.tag(fn, wt) + pw.encode_varint(len(v)) + v
    else:
        raise ValueError(f"unsupported wire type {wt}")


def _votes_only_differ_by_timestamp(last: bytes, new: bytes) -> Tuple[int, bool]:
    last_stripped, last_ts = _strip_timestamp_vote(last)
    new_stripped, _ = _strip_timestamp_vote(new)
    return last_ts, last_stripped == new_stripped


def _proposals_only_differ_by_timestamp(last: bytes, new: bytes) -> Tuple[int, bool]:
    last_stripped, last_ts = _strip_timestamp_proposal(last)
    new_stripped, _ = _strip_timestamp_proposal(new)
    return last_ts, last_stripped == new_stripped


def load_or_gen_file_pv(key_file_path: str, state_file_path: str) -> FilePV:
    """(file.go LoadOrGenFilePV)"""
    if os.path.exists(key_file_path):
        return FilePV.load(key_file_path, state_file_path)
    pv = FilePV.generate(key_file_path, state_file_path)
    pv.save()
    return pv
