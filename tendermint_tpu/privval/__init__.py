"""Validator key management (reference privval/, SURVEY.md §2.13)."""

from .file_pv import FilePV, load_or_gen_file_pv  # noqa: F401
