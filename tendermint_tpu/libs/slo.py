"""Declarative SLO engine: objectives over sliding windows, with breach
attribution against a seeded chaos schedule.

The chaos planes (faults, corruption, churn, crash, backpressure) assert
*invariants* — nothing forked, nothing double-signed. This module renders
the other judgment: did the fleet keep its *service levels* while all of
that was happening? An :class:`SLOSpec` declares objectives in a tiny
line grammar::

    # stream    agg    op  threshold   [window=SECONDS]
    commit_latency p99 <= 5.0 window=30
    caughtup       max <= 120
    rss_bytes      slope <= 8388608

Streams are plain named time series fed sample-by-sample into an
:class:`SLOEngine` (``feed(stream, t, value, node=...)``) from whatever
the caller already has — FleetScraper rollups, txlife sealed records,
stage-timeline deltas, watermark samples. ``evaluate()`` slides each
objective's window (hop = window/2) over every per-node series and emits
merged breach intervals.

Every breach is then *attributed*: :func:`attribute` intersects the
breach window with the chaos schedule (which plane was armed, which node
was dying, which links were black-holed) and with the slowest-stage
timeline, so an SLO miss names a plane, a node and a stage.
``unattributed`` is a loud first-class outcome, not a fallback: a breach
that overlaps no armed chaos window is exactly how slow leaks and
metric-cardinality blowups surface.

Fingerprints (:func:`breach_fingerprint`) strip wall-clock fields so two
same-seed runs can be diffed (tools/soak.py --verify-determinism).

Stdlib-only on purpose: tools/soak.py --self-test runs this on boxes
that can't import jax.
"""

from __future__ import annotations

import hashlib
import json
from typing import Callable, Dict, List, Optional, Tuple

AGGS = ("p50", "p90", "p99", "max", "min", "mean", "count", "slope", "last")
OPS = ("<=", ">=", "==", "<", ">")  # longest-match order for the parser

#: the soak plane's standard objectives (thresholds sized for an in-proc
#: fleet under concurrent multi-plane chaos on a shared CPU — generous on
#: latency, tight on "should never happen" counters and growth slopes).
DEFAULT_SPEC = """\
# stream            agg    op  threshold  window
commit_latency      p99    <=  20.0       window=30
caughtup            max    <=  120
queue_full_sheds    count  <=  0
rss_bytes           slope  <=  8388608
wal_bytes           slope  <=  4194304
ring_depth          max    <=  4096
metric_series       max    <=  8000
"""


class Objective:
    """One parsed spec line. ``window_s <= 0`` means whole-run."""

    __slots__ = ("stream", "agg", "op", "threshold", "window_s", "name")

    def __init__(self, stream: str, agg: str, op: str, threshold: float,
                 window_s: float = 0.0):
        if agg not in AGGS:
            raise ValueError(f"unknown aggregator {agg!r} (one of {AGGS})")
        if op not in OPS:
            raise ValueError(f"unknown op {op!r} (one of {OPS})")
        self.stream = stream
        self.agg = agg
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.name = f"{stream}_{agg}"

    def as_dict(self) -> dict:
        return {"name": self.name, "stream": self.stream, "agg": self.agg,
                "op": self.op, "threshold": self.threshold,
                "window_s": self.window_s}


class SLOSpec:
    """A parsed set of objectives."""

    def __init__(self, objectives: List[Objective]):
        self.objectives = objectives

    @classmethod
    def parse(cls, text: str) -> "SLOSpec":
        """Line grammar: ``<stream> <agg> <op> <value> [window=N]`` with
        ``#`` comments and blank lines ignored. Raises ValueError with
        the offending line number on any malformed line."""
        objectives = []
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) not in (4, 5):
                raise ValueError(f"spec line {lineno}: expected "
                                 f"'<stream> <agg> <op> <value> "
                                 f"[window=N]', got {raw!r}")
            stream, agg, op, value = parts[:4]
            window_s = 0.0
            if len(parts) == 5:
                if not parts[4].startswith("window="):
                    raise ValueError(
                        f"spec line {lineno}: trailing field must be "
                        f"window=N, got {parts[4]!r}")
                window_s = float(parts[4][len("window="):].rstrip("s"))
            try:
                threshold = float(value)
            except ValueError:
                raise ValueError(
                    f"spec line {lineno}: bad threshold {value!r}")
            try:
                objectives.append(
                    Objective(stream, agg, op, threshold, window_s))
            except ValueError as e:
                raise ValueError(f"spec line {lineno}: {e}")
        return cls(objectives)

    @classmethod
    def default(cls) -> "SLOSpec":
        return cls.parse(DEFAULT_SPEC)

    def as_dicts(self) -> List[dict]:
        return [o.as_dict() for o in self.objectives]


# -- aggregation --------------------------------------------------------------

def _percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile (matches tools/loadtime.py)."""
    s = sorted(vals)
    if not s:
        return 0.0
    k = max(0, min(len(s) - 1, int(round(q / 100.0 * len(s) + 0.5)) - 1))
    return s[k]


def _aggregate(pts: List[Tuple[float, float]], agg: str) -> float:
    """Reduce [(t, value), ...] (already window-filtered, time-sorted)."""
    vals = [v for _, v in pts]
    if agg == "count":
        return float(sum(vals))          # feed event deltas as values
    if not vals:
        return 0.0
    if agg == "max":
        return max(vals)
    if agg == "min":
        return min(vals)
    if agg == "mean":
        return sum(vals) / len(vals)
    if agg == "last":
        return vals[-1]
    if agg == "slope":
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return 0.0
        # growth rate clamped at zero: gauges legitimately dip (GC, WAL
        # rotation) and the leak objective only cares about net growth
        return max(0.0, (vals[-1] - vals[0]) / dt)
    return _percentile(vals, {"p50": 50.0, "p90": 90.0, "p99": 99.0}[agg])


def _violates(observed: float, op: str, threshold: float) -> bool:
    if op == "<=":
        return observed > threshold
    if op == "<":
        return observed >= threshold
    if op == ">=":
        return observed < threshold
    if op == ">":
        return observed <= threshold
    return observed != threshold         # "=="


def _worse(a: float, b: float, op: str) -> float:
    """Of two breaching observations, the one further past the bound."""
    return max(a, b) if op in ("<=", "<") else min(a, b)


# -- the engine ---------------------------------------------------------------

class SLOEngine:
    """Feed streams, evaluate objectives over sliding windows.

    Samples are (t, value, node) triples; ``node=None`` means
    cluster-level. Evaluation is pure over the fed samples — same
    streams in, same breaches out — which is what makes same-seed soak
    runs diffable by fingerprint."""

    MAX_WINDOWS = 100_000   # runaway-spec backstop, not a tuning knob

    def __init__(self, spec: Optional[SLOSpec] = None):
        self.spec = spec or SLOSpec.default()
        self._streams: Dict[str, List[Tuple[float, float, Optional[str]]]] = {}

    def feed(self, stream: str, t: float, value: float,
             node: Optional[str] = None) -> None:
        self._streams.setdefault(stream, []).append(
            (float(t), float(value), node))

    def feed_many(self, stream: str,
                  samples: List[Tuple[float, float]],
                  node: Optional[str] = None) -> None:
        for t, v in samples:
            self.feed(stream, t, v, node)

    def sample_counts(self) -> Dict[str, int]:
        return {k: len(v) for k, v in sorted(self._streams.items())}

    def _windows(self, obj: Objective,
                 pts: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
        t0, t1 = pts[0][0], pts[-1][0]
        if obj.window_s <= 0 or t1 - t0 <= obj.window_s:
            return [(t0, t1)]
        hop = obj.window_s / 2.0
        out, start, n = [], t0, 0
        while start < t1 and n < self.MAX_WINDOWS:
            out.append((start, start + obj.window_s))
            start += hop
            n += 1
        return out

    def evaluate(self) -> List[dict]:
        """All breaches, per objective per node, with consecutive
        breaching windows merged into one interval carrying the worst
        observation."""
        breaches: List[dict] = []
        for obj in self.spec.objectives:
            samples = self._streams.get(obj.stream, [])
            if not samples:
                continue
            groups: Dict[str, List[Tuple[float, float]]] = {}
            for t, v, node in samples:
                groups.setdefault(node or "cluster", []).append((t, v))
            for node in sorted(groups):
                pts = sorted(groups[node])
                run: Optional[dict] = None
                for w0, w1 in self._windows(obj, pts):
                    sel = [(t, v) for t, v in pts if w0 <= t <= w1]
                    if not sel:
                        continue
                    observed = _aggregate(sel, obj.agg)
                    if _violates(observed, obj.op, obj.threshold):
                        if run is not None and w0 <= run["window"][1]:
                            run["window"][1] = w1
                            run["observed"] = _worse(
                                run["observed"], observed, obj.op)
                        else:
                            if run is not None:
                                breaches.append(run)
                            run = {"objective": obj.name,
                                   "stream": obj.stream, "agg": obj.agg,
                                   "op": obj.op,
                                   "threshold": obj.threshold,
                                   "observed": round(observed, 6),
                                   "window": [w0, w1], "node": node}
                    elif run is not None:
                        breaches.append(run)
                        run = None
                if run is not None:
                    breaches.append(run)
        for b in breaches:
            b["observed"] = round(b["observed"], 6)
        return breaches


# -- attribution --------------------------------------------------------------

def _overlap(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def attribute(breach: dict, schedule: List[dict],
              stages: Optional[List[dict]] = None,
              min_cover: float = 1.0 / 3.0,
              total_span: Optional[float] = None) -> dict:
    """Name the plane/node/stage behind a breach, or say ``unattributed``
    out loud.

    ``schedule`` entries are armed chaos windows
    ``{"t0", "t1", "plane", "node"?, "detail"?}`` on the same clock as
    the breach window. Selection, in order:

    1. A breach spanning (>= 90% of) ``total_span`` — the whole run —
       is *global*, and a time-localized chaos window can't explain a
       global symptom: slow leaks and cardinality blowups stay loudly
       unattributed instead of pinned on whichever plane happened to be
       armed longest.
    2. Candidate events must cover at least ``min_cover`` of the breach
       window (sliding-window aggregates like p99 smear a spike by up to
       one window on each side, so the bound is deliberately looser than
       a majority). A zero-length breach — a single-point stream like a
       kill-to-caught-up measurement — qualifies any window containing
       it.
    3. Among qualifiers, the most *concentrated* wins — largest
       overlap-to-event-duration ratio, ties to the shorter event. When
       planes are armed concurrently (the whole point of a game day) a
       nested, more specific window beats the broad one above it.

    ``stages`` entries are slowest-stage records ``{"t0", "t1",
    "stage"}`` from the merged trace/stage-timeline machinery."""
    w0, w1 = breach["window"]
    span = max(0.0, w1 - w0)
    best = None
    if total_span is None or span < 0.9 * total_span:
        best_key = None
        for ev in schedule or ():
            e0, e1 = ev["t0"], ev["t1"]
            elen = max(e1 - e0, 1e-9)
            if span <= 0:
                if not (e0 <= w0 <= e1):
                    continue
                ov = 1e-9
            else:
                ov = _overlap(w0, w1, e0, e1)
                if e1 <= e0 and w0 <= e0 <= w1:
                    ov = max(ov, 1e-9)
                if ov < min_cover * span:
                    continue
            key = (ov / elen, -elen)
            if best_key is None or key > best_key:
                best, best_key = ev, key
    stage = "unknown"
    if stages:
        sbest, sov = None, 0.0
        for s in stages:
            ov = _overlap(w0, w1, s["t0"], s["t1"])
            if ov > sov:
                sbest, sov = s, ov
        if sbest is not None:
            stage = sbest["stage"]
    if best is None:
        return {"plane": "unattributed",
                "node": breach.get("node") or "cluster",
                "stage": stage, "detail": ""}
    return {"plane": best["plane"],
            "node": best.get("node") or breach.get("node") or "cluster",
            "stage": stage, "detail": best.get("detail", "")}


def attribute_all(breaches: List[dict], schedule: List[dict],
                  stages: Optional[List[dict]] = None,
                  total_span: Optional[float] = None) -> List[dict]:
    """Annotate every breach in place with its attribution; returns the
    list for chaining."""
    for b in breaches:
        b["attribution"] = attribute(b, schedule, stages,
                                     total_span=total_span)
    return breaches


# -- fingerprints -------------------------------------------------------------

def breach_fingerprint(breaches: List[dict]) -> str:
    """Wall-clock-stripped digest of WHAT breached and WHY — objective,
    node, plane, stage — so two same-seed runs diff clean even though
    their window timestamps and observed values never replay exactly."""
    keys = sorted(
        (b["objective"], b.get("node") or "cluster",
         (b.get("attribution") or {}).get("plane", "unattributed"),
         (b.get("attribution") or {}).get("stage", "unknown"))
        for b in breaches)
    blob = json.dumps(keys, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def schedule_fingerprint(plan: List[dict]) -> str:
    """Digest of a chaos schedule (offset-timestamped, so pure per seed)."""
    blob = json.dumps(plan, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]
