"""Import repo tools/*.py modules from inside the package or bench.py.

The operator toolbox (tools/trace_summary.py, trace_merge.py,
fleet_scrape.py, ...) is deliberately stdlib-only and lives OUTSIDE the
package so it runs on boxes that can't import jax. Harness code that wants
to reuse a tool in-process (bench.py breakdowns, the e2e runner's fleet
scraper) imports it through this one helper instead of each hand-rolling
the sys.path dance.
"""

from __future__ import annotations

import importlib
import os
import sys

TOOLS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))), "tools")


def load_tool(name: str):
    """Import ``tools/<name>.py`` as a module (tools is not a package)."""
    sys.path.insert(0, TOOLS_DIR)
    try:
        return importlib.import_module(name)
    finally:
        sys.path.remove(TOOLS_DIR)
