"""BitArray (reference libs/bits/bit_array.go) — vote/part presence masks.

Backed by a Python int bitmask; converts to numpy bool arrays for the device
tally plane (SURVEY.md §2.15: "maps to device-friendly masks").
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class BitArray:
    __slots__ = ("bits", "_mask")

    def __init__(self, bits: int):
        if bits < 0:
            bits = 0
        self.bits = bits
        self._mask = 0

    @staticmethod
    def from_indices(bits: int, indices) -> "BitArray":
        ba = BitArray(bits)
        for i in indices:
            ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i >= self.bits or i < 0:
            return False
        return bool((self._mask >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i >= self.bits or i < 0:
            return False
        if v:
            self._mask |= 1 << i
        else:
            self._mask &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._mask = self._mask
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(max(self.bits, other.bits))
        ba._mask = self._mask | other._mask
        return ba

    def and_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(min(self.bits, other.bits))
        ba._mask = self._mask & other._mask & ((1 << ba.bits) - 1)
        return ba

    def not_(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._mask = ~self._mask & ((1 << self.bits) - 1)
        return ba

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits in self but not in other (bit_array.go Sub)."""
        ba = BitArray(self.bits)
        mask_o = other._mask & ((1 << min(self.bits, other.bits)) - 1)
        ba._mask = self._mask & ~mask_o
        return ba

    def is_empty(self) -> bool:
        return self._mask == 0

    def is_full(self) -> bool:
        return self.bits > 0 and self._mask == (1 << self.bits) - 1

    def pick_random(self, rng: Optional[random.Random] = None) -> "tuple[int, bool]":
        """A uniformly random set bit, or (0, False) if none (bit_array.go PickRandom)."""
        idxs = self.true_indices()
        if not idxs:
            return 0, False
        r = rng or random
        return r.choice(idxs), True

    def true_indices(self) -> List[int]:
        m = self._mask
        out = []
        i = 0
        while m:
            if m & 1:
                out.append(i)
            m >>= 1
            i += 1
        return out

    def num_true(self) -> int:
        return bin(self._mask).count("1")

    def to_numpy(self) -> np.ndarray:
        out = np.zeros(self.bits, dtype=bool)
        for i in self.true_indices():
            out[i] = True
        return out

    def update(self, other: "BitArray") -> None:
        """Copy other's contents (truncated/extended to self.bits)."""
        self._mask = other._mask & ((1 << self.bits) - 1)

    def __eq__(self, other):
        return isinstance(other, BitArray) and self.bits == other.bits and self._mask == other._mask

    def __repr__(self):
        return "BA{" + "".join("x" if self.get_index(i) else "_" for i in range(self.bits)) + "}"

    def encode(self) -> bytes:
        """Proto BitArray (libs/bits/types.pb.go): int64 bits=1, repeated uint64 elems=2."""
        from . import protowire as pw

        w = pw.Writer()
        w.varint(1, self.bits)
        n_words = (self.bits + 63) // 64
        if n_words:
            # repeated uint64 packed
            body = b"".join(
                pw.encode_varint((self._mask >> (64 * k)) & ((1 << 64) - 1))
                for k in range(n_words)
            )
            w.bytes(2, body)
        return w.finish()

    @staticmethod
    def decode(data: bytes) -> "BitArray":
        from . import protowire as pw

        bits = 0
        words: List[int] = []
        for fn, wt, v in pw.iter_fields(data):
            if fn == 1:
                bits = pw.varint_to_int64(v)
            elif fn == 2:
                if wt == pw.WIRE_BYTES:
                    pos = 0
                    while pos < len(v):
                        word, pos = pw.decode_varint(v, pos)
                        words.append(word)
                else:
                    words.append(v)
        ba = BitArray(bits)
        mask = 0
        for k, word in enumerate(words):
            mask |= word << (64 * k)
        ba._mask = mask & ((1 << bits) - 1) if bits else 0
        return ba
