"""Restart supervision with bounded exponential backoff — the crash-recovery
plane's policy engine.

A node that dies at a durability boundary must come BACK (and its recovery
must be measurable), but a node that dies instantly every time it comes
back must NOT be restarted forever: that is a crash loop, and the right
move is to stop, keep the evidence, and page an operator. This module is
the shared decision core for both harnesses:

* the e2e ``Runner`` supervises SUBPROCESS nodes whose manifest says
  ``restart_policy = "on-failure"`` (``e2e/runner.py poll_restarts``);
* the in-proc crash matrix (``tools/crashmatrix.py``) supervises rig nodes
  it kills at fail points and rebuilds from their home dirs.

Policy semantics (manifest keys map 1:1):

* ``policy``       — ``"never"`` (default: a dead node stays dead, today's
                     behavior) or ``"on-failure"`` (restart on any
                     non-clean exit).
* ``max_restarts`` — consecutive-fast-crash budget: after this many
                     crashes WITHOUT an intervening healthy run the
                     supervisor gives up (``gave_up``) and the harness
                     writes a crash-loop debugdump bundle.
* ``backoff_s``    — base delay; the i-th consecutive crash waits
                     ``backoff_s * 2**i`` capped at ``backoff_max_s``.
* ``healthy_uptime_s`` — an exit after at least this much uptime resets
                     the consecutive counter: an occasional crasher earns
                     its budget back, an instant crasher burns through it.

All decisions are pure functions of (policy, exit history, clock) — the
supervisor takes an injectable ``time_fn`` so unit tests and the seeded
crash matrix stay deterministic.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

RESTART_POLICIES = ("never", "on-failure")


@dataclass
class RestartPolicy:
    policy: str = "never"
    max_restarts: int = 3
    backoff_s: float = 0.5
    backoff_max_s: float = 30.0
    healthy_uptime_s: float = 30.0

    def validate(self) -> None:
        if self.policy not in RESTART_POLICIES:
            raise ValueError(f"unknown restart policy {self.policy!r}; "
                             f"known: {RESTART_POLICIES}")
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if self.backoff_s <= 0 or self.backoff_max_s < self.backoff_s:
            raise ValueError("need 0 < backoff_s <= backoff_max_s")
        if self.healthy_uptime_s < 0:
            raise ValueError("healthy_uptime_s must be >= 0")

    def delay(self, consecutive_crashes: int) -> float:
        """Backoff before the restart that follows the Nth consecutive
        crash (1-based): backoff_s * 2**(n-1), capped."""
        n = max(1, consecutive_crashes)
        return min(self.backoff_max_s, self.backoff_s * (2.0 ** (n - 1)))

    def schedule(self) -> List[float]:
        """The full backoff schedule a crash-looping child walks before
        the supervisor gives up."""
        return [self.delay(i + 1) for i in range(self.max_restarts)]


@dataclass
class ExitRecord:
    at: float
    uptime_s: float
    exit_code: int
    reason: str
    action: str  # "restart" | "give-up" | "stop" | "clean"


class RestartSupervisor:
    """Tracks one child's launch/exit lifecycle and decides restarts.

    Usage::

        sup = RestartSupervisor(policy, name="validator3")
        sup.on_launch()
        ...child exits with rc...
        delay = sup.on_exit(rc)     # None = do not restart
        if delay is None and sup.gave_up: write_crashloop_bundle(...)
    """

    def __init__(self, policy: RestartPolicy, name: str = "node",
                 time_fn: Callable[[], float] = time.monotonic):
        policy.validate()
        self.policy = policy
        self.name = name
        self._now = time_fn
        self._launched_at: Optional[float] = None
        self.restarts = 0            # restarts actually granted
        self.consecutive_crashes = 0  # fast crashes since last healthy run
        self.gave_up = False
        self.history: List[ExitRecord] = []

    def on_launch(self) -> None:
        self._launched_at = self._now()

    def on_exit(self, exit_code: int,
                clean_exit_codes: tuple = (0,)) -> Optional[float]:
        """Record an exit; returns the backoff seconds to wait before
        relaunching, or None when the child must stay down (clean exit,
        policy "never", or crash-loop give-up — check ``gave_up``)."""
        now = self._now()
        uptime = (now - self._launched_at) if self._launched_at is not None \
            else 0.0
        self._launched_at = None
        if exit_code in clean_exit_codes:
            self.consecutive_crashes = 0
            self._record(now, uptime, exit_code, "clean", "clean")
            return None
        reason = "crash" if exit_code >= 0 else f"signal-{-exit_code}"
        if self.policy.policy == "never":
            self._record(now, uptime, exit_code, reason, "stop")
            return None
        if self.gave_up:
            self._record(now, uptime, exit_code, reason, "give-up")
            return None
        if uptime >= self.policy.healthy_uptime_s:
            # a healthy run re-earns the crash budget
            self.consecutive_crashes = 0
        self.consecutive_crashes += 1
        if self.consecutive_crashes > self.policy.max_restarts:
            self.gave_up = True
            self._record(now, uptime, exit_code, reason, "give-up")
            return None
        self.restarts += 1
        self._record(now, uptime, exit_code, reason, "restart")
        return self.policy.delay(self.consecutive_crashes)

    def _record(self, at: float, uptime: float, rc: int, reason: str,
                action: str) -> None:
        self.history.append(ExitRecord(at, round(uptime, 3), rc, reason,
                                       action))

    def summary(self) -> Dict:
        return {
            "name": self.name,
            "policy": self.policy.policy,
            "restarts": self.restarts,
            "consecutive_crashes": self.consecutive_crashes,
            "gave_up": self.gave_up,
            "history": [vars(r) for r in self.history],
        }


def policy_from_manifest(nm) -> RestartPolicy:
    """Build a policy from an e2e NodeManifest's restart keys."""
    return RestartPolicy(policy=nm.restart_policy,
                         max_restarts=nm.max_restarts,
                         backoff_s=nm.backoff_s)


def write_crashloop_bundle(out_dir: str, sup: "RestartSupervisor",
                           extras: Optional[Dict[str, str]] = None,
                           log_path: Optional[str] = None,
                           log_tail_bytes: int = 65536) -> str:
    """The give-up artifact: a JSON bundle with the full exit history plus
    the tail of the child's log — what an operator (or a postmortem) needs
    to see WHY the supervisor stopped trying. Returns the bundle path."""
    os.makedirs(out_dir, exist_ok=True)
    doc = {"crashloop": sup.summary(), "extras": extras or {}}
    if log_path and os.path.exists(log_path):
        try:
            with open(log_path, "rb") as f:
                f.seek(max(0, os.path.getsize(log_path) - log_tail_bytes))
                doc["log_tail"] = f.read().decode(errors="replace")
        except OSError as e:
            doc["log_tail_error"] = str(e)
    path = os.path.join(out_dir, f"crashloop-{sup.name}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
    return path
