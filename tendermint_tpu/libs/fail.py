"""Crash-point injection (reference libs/fail/fail.go): the commit path is
sprinkled with ``fail_point()`` calls; setting ``TMTPU_FAIL_INDEX=N`` kills
the process at the Nth point reached, so crash-consistency tests can murder
a node at every interesting boundary (reference sites:
state/execution.go:149,156,188,196, consensus/state.go:776).
"""

from __future__ import annotations

import os
import sys

_counter = 0


def fail_index() -> int:
    v = os.environ.get("TMTPU_FAIL_INDEX")
    return int(v) if v else -1


def fail_point() -> None:
    """(fail.go Fail) exit(1) when the configured index is reached."""
    global _counter
    idx = fail_index()
    if idx < 0:
        return
    if _counter == idx:
        sys.stderr.write(f"*** fail point {idx} reached: exiting ***\n")
        sys.stderr.flush()
        os._exit(1)
    _counter += 1


def reset() -> None:
    global _counter
    _counter = 0
