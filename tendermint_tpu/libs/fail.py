"""Crash-point injection (reference libs/fail/fail.go): the commit path is
sprinkled with ``fail_point()`` calls; setting ``TMTPU_FAIL_INDEX=N`` kills
the process at the Nth point reached, so crash-consistency tests can murder
a node at every interesting boundary (reference sites:
state/execution.go:149,156,188,196, consensus/state.go:776).

Three trigger forms:

* index — ``TMTPU_FAIL_INDEX=N``: die at the Nth fail point reached,
  whichever it is (the crash-matrix sweep);
* named — ``TMTPU_FAIL_POINT=<site>``: die the first time the point with
  that name is reached (``fail_point("consensus.commit.before_end_height")``),
  so a test can target one boundary without counting its way there;
* in-proc — ``arm_raise(<site>)``: the first reach of that named point
  raises :class:`KilledAtFailPoint` (a BaseException, so defensive
  ``except Exception`` blocks can't swallow it) instead of exiting, and
  the arming is consumed. This is how in-proc fleets (tools/crashmatrix.py)
  SIGKILL one node of a shared-process net: the victim's task dies at the
  boundary while the survivors' tasks keep running; the rig then freezes
  the victim's fds (dup2 → /dev/null, discarding unflushed buffers exactly
  like a real SIGKILL would) and rebuilds it from its home dir.

The counter is lock-protected: fail points sit on the consensus loop AND
on apply-plane worker threads, and a racy double-increment would make the
crash matrix skip boundaries. Test fixtures call :func:`reset` so
counters (and in-proc armings) don't leak between tests (see
tests/conftest.py).

For non-fatal, probabilistic, seeded injection see libs/faults.py — this
module is only the kill switch.
"""

from __future__ import annotations

import contextvars
import os
import sys
import threading
from typing import Optional

#: in-proc kill scoping: a rig sets ``scope.set("victim")`` around the
#: victim's task creation (asyncio tasks inherit the creating context), so
#: an armed boundary in SHARED code (execution, commit) kills only the
#: victim's tasks while survivors sharing the process sail past it.
#: Default None = unscoped; arm_raise(scope_token=None) fires everywhere.
scope: contextvars.ContextVar = contextvars.ContextVar(
    "tmtpu_fail_scope", default=None)

#: every named fail point production code actually reaches — the durability
#: boundary catalog tools/crashmatrix.py enumerates and e2e manifests
#: validate ``fail_point =`` against (a typo'd name never fires and the
#: crash cell passes vacuously, so arming validates against this).
KNOWN_FAIL_POINTS = frozenset({
    "execution.before_exec_block",       # state/execution.py (execution.go:149)
    "execution.after_state_save",        # state/execution.py (execution.go:196)
    "consensus.commit.before_end_height",  # consensus/state.py (state.go:776)
    "wal.before_fsync",                  # consensus/wal.py: record appended+
                                         # flushed, durability not yet claimed
    "wal.after_fsync",                   # consensus/wal.py: records durable,
                                         # nothing has acted on them yet
    "wal.mid_group_commit",              # consensus/wal.py: >=1 record of a
                                         # group appended, batch flush pending
    "db.mid_window_flush",               # libs/db.py SQLiteDB.write_batch:
                                         # batch staged in the txn, not committed
    "privval.between_sign_and_save",     # privval/file_pv.py: signature
                                         # computed, last-sign-state not saved
    "statesync.mid_chunk_apply",         # statesync/syncer.py: >=1 chunk
                                         # applied, restore incomplete
    "prune.mid_blocks",                  # store/block_store.py: prune deletes
                                         # enumerated, batch not applied
})

_counter = 0
_lock = threading.Lock()
_armed_raise: Optional[str] = None
_armed_scope: Optional[str] = None
_killed_at: Optional[str] = None


class KilledAtFailPoint(BaseException):
    """In-proc process death at a fail point. BaseException on purpose: a
    real SIGKILL doesn't ask the victim's ``except Exception`` blocks for
    permission, so the simulated one must not either."""

    def __init__(self, site: str):
        super().__init__(f"killed at fail point {site!r}")
        self.site = site


def fail_index() -> int:
    v = os.environ.get("TMTPU_FAIL_INDEX")
    return int(v) if v else -1


def fail_point(name: Optional[str] = None) -> None:
    """(fail.go Fail) exit(1) when the configured index — or, for named
    points, the configured TMTPU_FAIL_POINT site — is reached; raise
    KilledAtFailPoint when the point was armed in-proc via arm_raise."""
    global _counter, _armed_raise, _killed_at
    if _armed_raise is not None and name is not None:
        fire = False
        with _lock:
            if _armed_raise == name and (
                    _armed_scope is None or scope.get() == _armed_scope):
                _armed_raise = None  # one-shot: the restarted victim, same
                _killed_at = name    # process, must not re-die here
                fire = True
        if fire:
            raise KilledAtFailPoint(name)
    named = os.environ.get("TMTPU_FAIL_POINT")
    if named and name is not None and named == name:
        _die(f"named fail point {name!r} reached")
    idx = fail_index()
    if idx < 0:
        return
    with _lock:
        hit = _counter == idx
        _counter += 1
    if hit:
        _die(f"fail point {idx} reached")


def arm_raise(name: str, scope_token: Optional[str] = None) -> None:
    """Arm ONE named point to raise KilledAtFailPoint at its next reach
    (one-shot; replaces any previous arming). In-proc analog of
    TMTPU_FAIL_POINT for fleets sharing a process. ``scope_token`` limits
    the kill to tasks whose ``fail.scope`` contextvar equals it — how a
    rig kills ONE node of a shared-process fleet at a boundary that sits
    in code every node runs."""
    global _armed_raise, _armed_scope, _killed_at
    with _lock:
        _armed_raise = name
        _armed_scope = scope_token
        _killed_at = None


def killed_at() -> Optional[str]:
    """The site the last arm_raise kill fired at (None = hasn't fired)."""
    with _lock:
        return _killed_at


def armed() -> Optional[str]:
    with _lock:
        return _armed_raise


def _die(why: str) -> None:
    sys.stderr.write(f"*** {why}: exiting ***\n")
    sys.stderr.flush()
    os._exit(1)


def reset() -> None:
    global _counter, _armed_raise, _armed_scope, _killed_at
    with _lock:
        _counter = 0
        _armed_raise = None
        _armed_scope = None
        _killed_at = None


def counter() -> int:
    with _lock:
        return _counter
