"""Crash-point injection (reference libs/fail/fail.go): the commit path is
sprinkled with ``fail_point()`` calls; setting ``TMTPU_FAIL_INDEX=N`` kills
the process at the Nth point reached, so crash-consistency tests can murder
a node at every interesting boundary (reference sites:
state/execution.go:149,156,188,196, consensus/state.go:776).

Two trigger forms:

* index — ``TMTPU_FAIL_INDEX=N``: die at the Nth fail point reached,
  whichever it is (the crash-matrix sweep);
* named — ``TMTPU_FAIL_POINT=<site>``: die the first time the point with
  that name is reached (``fail_point("consensus.commit.before_end_height")``),
  so a test can target one boundary without counting its way there.

The counter is lock-protected: fail points sit on the consensus loop AND
on apply-plane worker threads, and a racy double-increment would make the
crash matrix skip boundaries. Test fixtures call :func:`reset` so
counters don't leak between tests (see tests/conftest.py).

For non-fatal, probabilistic, seeded injection see libs/faults.py — this
module is only the kill switch.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

_counter = 0
_lock = threading.Lock()


def fail_index() -> int:
    v = os.environ.get("TMTPU_FAIL_INDEX")
    return int(v) if v else -1


def fail_point(name: Optional[str] = None) -> None:
    """(fail.go Fail) exit(1) when the configured index — or, for named
    points, the configured TMTPU_FAIL_POINT site — is reached."""
    global _counter
    named = os.environ.get("TMTPU_FAIL_POINT")
    if named and name is not None and named == name:
        _die(f"named fail point {name!r} reached")
    idx = fail_index()
    if idx < 0:
        return
    with _lock:
        hit = _counter == idx
        _counter += 1
    if hit:
        _die(f"fail point {idx} reached")


def _die(why: str) -> None:
    sys.stderr.write(f"*** {why}: exiting ***\n")
    sys.stderr.flush()
    os._exit(1)


def reset() -> None:
    global _counter
    with _lock:
        _counter = 0


def counter() -> int:
    with _lock:
        return _counter
