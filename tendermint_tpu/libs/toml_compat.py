"""TOML reading that works on Python 3.10 containers.

Stdlib ``tomllib`` exists only from 3.11; this repo's TOML consumers
(node config, e2e manifests) mostly read files the repo ITSELF wrote
(``Config.to_toml``, ``e2e/generate.doc_to_toml``) — a flat subset:
``key = value`` lines, ``[section]`` / ``[dotted.section]`` headers,
full-line or trailing comments, and values that are quoted strings,
booleans, integers, floats, or one-line lists thereof. When ``tomllib``
is available it is used verbatim; otherwise :func:`loads` parses exactly
that subset, so subprocess localnets (bench ``ingest``, the e2e runner,
``cmd testnet``) run on 3.10 images instead of dying at import.
"""

from __future__ import annotations

from typing import Any, Dict

try:
    import tomllib as _tomllib
except ModuleNotFoundError:  # pragma: no cover - depends on interpreter
    _tomllib = None


class TOMLDecodeError(ValueError):
    pass


def load(f) -> Dict[str, Any]:
    data = f.read()
    if isinstance(data, bytes):
        data = data.decode()
    return loads(data)


def loads(text: str) -> Dict[str, Any]:
    if _tomllib is not None:
        try:
            return _tomllib.loads(text)
        except _tomllib.TOMLDecodeError as e:
            raise TOMLDecodeError(str(e)) from e
    return _loads_subset(text)


def _loads_subset(text: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    current = root
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            current = root
            for part in line[1:-1].strip().split("."):
                part = part.strip().strip('"')
                if not part:
                    raise TOMLDecodeError(f"line {lineno}: empty table name")
                current = current.setdefault(part, {})
            continue
        key, eq, value = line.partition("=")
        if not eq:
            raise TOMLDecodeError(f"line {lineno}: expected key = value")
        current[key.strip().strip('"')] = _value(value.strip(), lineno)
    return root


def _strip_comment(line: str) -> str:
    """Drop a trailing comment — a ``#`` outside any quoted string."""
    out = []
    in_str = False
    for ch in line:
        if ch == '"' and (not out or out[-1] != "\\"):
            in_str = not in_str
        elif ch == "#" and not in_str:
            break
        out.append(ch)
    return "".join(out)


def _value(tok: str, lineno: int) -> Any:
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok in ("true", "false"):
        return tok == "true"
    if tok.startswith("[") and tok.endswith("]"):
        inner = tok[1:-1].strip()
        if not inner:
            return []
        return [_value(p.strip(), lineno) for p in _split_list(inner)]
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        raise TOMLDecodeError(f"line {lineno}: cannot parse value {tok!r}")


def _split_list(inner: str):
    """Split a one-line list body on commas outside quotes."""
    parts, buf, in_str = [], [], False
    for ch in inner:
        if ch == '"' and (not buf or buf[-1] != "\\"):
            in_str = not in_str
        if ch == "," and not in_str:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts
