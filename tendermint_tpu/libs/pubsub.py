"""Event pubsub with the reference's query language
(reference libs/pubsub/pubsub.go:90 + query/query.peg).

Grammar (same operator set as the reference — AND only, no OR):
    cond   := tag op value
    op     := '=' | '<' | '<=' | '>' | '>=' | 'CONTAINS' | 'EXISTS'
    query  := cond (AND cond)*
    value  := 'string' | number | TIME t | DATE d
Events carry a message plus tags: Dict[str, List[str]] (composite keys like
"tx.height" → values). Matching follows libs/pubsub/query/query.go: a
condition matches if ANY value under the key satisfies it.
"""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<and>AND\b)|(?P<op><=|>=|=|<|>|CONTAINS\b|EXISTS\b)|"
    r"(?P<str>'(?:[^'])*')|(?P<num>-?\d+(?:\.\d+)?)|"
    r"(?P<key>[A-Za-z_][A-Za-z0-9_.\-]*))"
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: Any  # str | float | None (EXISTS)


class Query:
    """Compiled query (reference libs/pubsub/query/query.go Query)."""

    def __init__(self, source: str):
        self.source = source.strip()
        self.conditions: List[Condition] = _parse(self.source) if self.source else []

    def matches(self, events: Dict[str, List[str]]) -> bool:
        return all(_match_condition(c, events) for c in self.conditions)

    def __str__(self) -> str:
        return self.source

    def __eq__(self, other):
        return isinstance(other, Query) and self.source == other.source

    def __hash__(self):
        return hash(self.source)


def _parse(s: str) -> List[Condition]:
    pos = 0
    conds: List[Condition] = []
    n = len(s)
    while pos < n:
        key, pos = _expect(s, pos, "key")
        op, pos = _expect(s, pos, "op")
        if op == "EXISTS":
            conds.append(Condition(key, op, None))
        else:
            m = _TOKEN_RE.match(s, pos)
            if not m or (not m.group("str") and not m.group("num")):
                raise ValueError(f"query parse error at {pos}: expected value in {s!r}")
            pos = m.end()
            if m.group("str"):
                conds.append(Condition(key, op, m.group("str")[1:-1]))
            else:
                conds.append(Condition(key, op, float(m.group("num"))))
        if pos < n:
            m = _TOKEN_RE.match(s, pos)
            if not m or not m.group("and"):
                raise ValueError(f"query parse error at {pos}: expected AND in {s!r}")
            pos = m.end()
    return conds


def _expect(s: str, pos: int, kind: str) -> Tuple[str, int]:
    m = _TOKEN_RE.match(s, pos)
    if not m or not m.group(kind):
        raise ValueError(f"query parse error at {pos}: expected {kind} in {s!r}")
    return m.group(kind), m.end()


def _match_condition(c: Condition, events: Dict[str, List[str]]) -> bool:
    values = events.get(c.key)
    if values is None:
        return False
    if c.op == "EXISTS":
        return True
    for v in values:
        if c.op == "=":
            if isinstance(c.value, float):
                try:
                    if float(v) == c.value:
                        return True
                except ValueError:
                    pass
            elif v == c.value:
                return True
        elif c.op == "CONTAINS":
            if isinstance(c.value, str) and c.value in v:
                return True
        else:  # numeric comparisons
            try:
                fv = float(v)
            except ValueError:
                continue
            if ((c.op == "<" and fv < c.value) or (c.op == "<=" and fv <= c.value)
                    or (c.op == ">" and fv > c.value) or (c.op == ">=" and fv >= c.value)):
                return True
    return False


# ---------------------------------------------------------------------------

@dataclass
class Message:
    data: Any
    events: Dict[str, List[str]] = field(default_factory=dict)


class Subscription:
    """Per-subscriber buffered queue (pubsub.go:29 Subscription)."""

    def __init__(self, out_capacity: int = 100):
        self.queue: "asyncio.Queue[Message]" = asyncio.Queue(maxsize=out_capacity)
        self._canceled = asyncio.Event()
        self.err: Optional[str] = None

    async def next(self) -> Message:
        get = asyncio.ensure_future(self.queue.get())
        cancel = asyncio.ensure_future(self._canceled.wait())
        done, pending = await asyncio.wait({get, cancel},
                                           return_when=asyncio.FIRST_COMPLETED)
        for p in pending:
            p.cancel()
        if get in done:
            return get.result()
        raise SubscriptionCanceled(self.err or "subscription canceled")

    def cancel(self, reason: str = "") -> None:
        self.err = reason
        self._canceled.set()

    @property
    def canceled(self) -> bool:
        return self._canceled.is_set()


class SubscriptionCanceled(Exception):
    pass


class PubSubServer:
    """(libs/pubsub/pubsub.go:90 Server) — subscriber × query routing.

    Async-native: publish never blocks the publisher; a full subscriber
    buffer cancels that subscriber (the reference's ErrOutOfCapacity path).
    """

    def __init__(self):
        # (subscriber_id, query) -> Subscription
        self._subs: Dict[Tuple[str, Query], Subscription] = {}

    def subscribe(self, subscriber: str, query: Query,
                  out_capacity: int = 100) -> Subscription:
        key = (subscriber, query)
        if key in self._subs:
            raise ValueError(f"already subscribed: {subscriber} to {query}")
        sub = Subscription(out_capacity)
        self._subs[key] = sub
        return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        sub = self._subs.pop((subscriber, query), None)
        if sub is None:
            raise ValueError("subscription not found")
        sub.cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        keys = [k for k in self._subs if k[0] == subscriber]
        if not keys:
            raise ValueError("subscription not found")
        for k in keys:
            self._subs.pop(k).cancel("unsubscribed")

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        return sum(1 for k in self._subs if k[0] == subscriber)

    def publish(self, data: Any, events: Optional[Dict[str, List[str]]] = None) -> None:
        events = events or {}
        msg = Message(data, events)
        dead = []
        for (subscriber, query), sub in self._subs.items():
            if sub.canceled:
                dead.append((subscriber, query))
                continue
            if query.matches(events):
                try:
                    sub.queue.put_nowait(msg)
                except asyncio.QueueFull:
                    sub.cancel("out of capacity")
                    dead.append((subscriber, query))
        for k in dead:
            self._subs.pop(k, None)
