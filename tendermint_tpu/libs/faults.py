"""Deterministic, seeded fault injection — the fault plane.

``libs/fail.py`` can only murder the process at a counter; real failure
modes are partial: a device call that raises, an fsync that returns EIO, a
link that eats a packet. This module gives every such failure surface a
NAMED SITE that production code consults in one call:

    from ..libs.faults import faults
    faults.inject("wal.fsync", _EIO)     # raises iff the site is armed

Sites ship disabled: ``faults`` is a singleton whose hot-path check is one
truthiness test on an empty dict, so instrumented code pays nothing in
production. Arming happens from the environment::

    TMTPU_FAULTS="wal.fsync*1+2,device.batch_verify@0.25"
    TMTPU_FAULTS_SEED=7

or programmatically (tests): ``faults.configure("db.write_batch*1")``.

Grammar — comma-separated site specs, each ``site[@prob][*count][+skip]``:

* ``site``        fire on every evaluation (prob 1, unlimited)
* ``site@0.1``    fire with probability 0.1 per evaluation
* ``site*3``      fire at most 3 times, then go quiet
* ``site+5``      skip the first 5 evaluations before arming
* modifiers combine: ``wal.fsync@0.5*2+1``

Determinism: each site draws from its own ``random.Random`` seeded by
(global seed, site name), so a failing chaos run replays EXACTLY by
re-running with the same TMTPU_FAULTS/TMTPU_FAULTS_SEED pair — regardless
of how other sites interleave or what order threads evaluate. All state is
lock-protected; sites are evaluated from reactor tasks, executor threads,
and the consensus loop alike.

Known sites (the catalog; see README "Fault injection & chaos testing"):

* ``device.batch_verify`` — BatchVerifier's device dispatch (crypto/batch.py)
* ``device.lane.<label>`` — ONE multi-device pool lane (site family, e.g.
                            ``device.lane.tpu:3``; multidevice.py)
* ``device.vote_flush``   — vote micro-batcher device flush (vote_batcher.py)
* ``wal.fsync``           — consensus WAL fsync (consensus/wal.py)
* ``db.write_batch``      — KV write batches: BufferedDB window flush and
                            SQLiteDB write_batch (libs/db.py)
* ``net.drop``            — in-proc transport delivery (p2p/inproc.py)
* ``clock.skew``          — per-node deterministic wall-clock offset for
                            vote/proposal timestamping (consensus/state.py;
                            value-returning — consulted via ``skew_ns``,
                            the ``@prob`` modifier scales the ±500ms
                            magnitude window instead of gating firing)

Content-corruption sites (the adversarial plane — ``mutate`` flips a
deterministically-chosen bit instead of raising, so the victim's REAL
verification path runs against the tampered bytes):

* ``net.corrupt``             — payload tampering at in-proc transport
                                delivery (p2p/inproc.py)
* ``statesync.lying_snapshot`` — serving reactor advertises a snapshot
                                with a bogus hash (statesync/reactor.py)
* ``statesync.lying_chunk``   — serving reactor returns corrupted chunk
                                bytes (statesync/reactor.py)
* ``blocksync.bad_block``     — serving reactor returns a tampered block
                                response (blockchain/reactor.py)

All four are injected at the SERVER so the syncing/receiving node — the
victim — exercises its production verification + peer-banning paths.

Torn-write sites (the crash plane — ``tear`` truncates a payload at a
seeded prefix and may append a seeded garbage suffix, modeling a write
the process died in the middle of, so the victim's CRC-bounded replay,
repair-on-open, atomic-rename, and WAL-replay paths run against REAL
partial data instead of clean exceptions):

* ``wal.torn_write``    — consensus WAL record emit, group commits
                          included (consensus/wal.py)
* ``db.torn_write``     — KV write batches: a seeded PREFIX of the batch
                          lands before the failure (libs/db.py — the
                          batch-level analog of a byte tear)
* ``privval.torn_state`` — last-sign-state atomic write
                          (privval/file_pv.py)
* ``mempool.wal_torn``  — MempoolWAL tx-line emit
                          (mempool/clist_mempool.py)
"""

from __future__ import annotations

import logging
import os
import random
import threading
import zlib
from typing import Callable, Dict, List, Optional

ENV_SPEC = "TMTPU_FAULTS"
ENV_SEED = "TMTPU_FAULTS_SEED"

#: every site production code actually consults (the docstring catalog).
#: Site names are intentionally open — tests arm ad-hoc names — but a
#: typo'd name in an operator-facing spec arms nothing and the chaos run
#: passes vacuously, so env/manifest arming validates against this.
KNOWN_SITES = frozenset({
    "device.batch_verify",
    "device.vote_flush",
    "wal.fsync",
    "db.write_batch",
    "net.drop",
    # seeded per-node clock skew (consensus timestamping); value-returning
    # via skew_ns(), not a fire()-gated raise
    "clock.skew",
    # conflict-group mis-assignment (state/parallel.py): a fired trigger
    # tosses a tx into a deliberately wrong speculation lane, forcing the
    # validation + re-execution machinery to earn the byte-parity
    # invariant instead of riding correct hints
    "exec.conflict",
    # BLS aggregate-verify device path (crypto/bls12381/vec.py): a fired
    # site strikes the jax apk aggregation, opening the device breaker and
    # forcing the host scalar fallback — the verdict must not change
    "crypto.bls_verify",
    # content-corruption (adversarial) sites — consulted via mutate()
    "net.corrupt",
    "statesync.lying_snapshot",
    "statesync.lying_chunk",
    "blocksync.bad_block",
    # lying light-block server (light/serve.py): a fired site swaps the
    # served header for a tampered/forged one — witness cross-check must
    # catch it and strike the liar on the peerscore ledger
    "lightserve.lying_server",
    # torn-write (crash) sites — consulted via tear()/tear_index()
    "wal.torn_write",
    "db.torn_write",
    "privval.torn_state",
    "mempool.wal_torn",
})

#: site-name prefixes that are known as a FAMILY: the multi-device
#: dispatcher consults one site per device lane
#: (``device.lane.<platform>:<id>``, e.g. ``device.lane.tpu:3``), so a
#: chaos run can arm exactly one chip and watch the pool degrade to the
#: healthy peers. Exact names can't be enumerated — device topology is a
#: runtime fact.
KNOWN_SITE_PREFIXES = ("device.lane.",)


def is_known_site(name: str) -> bool:
    return name in KNOWN_SITES or name.startswith(KNOWN_SITE_PREFIXES)

logger = logging.getLogger("tmtpu.faults")


class InjectedFault(Exception):
    """Raised by an armed site with no caller-supplied exception factory."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site!r}")
        self.site = site


class _Site:
    __slots__ = ("name", "prob", "count", "skip", "rng", "evals", "fires")

    def __init__(self, name: str, prob: float, count: Optional[int],
                 skip: int, seed: int):
        self.name = name
        self.prob = prob
        self.count = count          # None = unlimited
        self.skip = skip
        # per-site stream: other sites' draws can't perturb this one's
        self.rng = random.Random(zlib.crc32(f"{seed}|{name}".encode()))
        self.evals = 0
        self.fires = 0

    def evaluate(self) -> bool:
        self.evals += 1
        if self.evals <= self.skip:
            return False
        if self.count is not None and self.fires >= self.count:
            return False
        if self.prob < 1.0 and self.rng.random() >= self.prob:
            return False
        self.fires += 1
        return True


def _parse_spec(spec: str, seed: int) -> Dict[str, _Site]:
    sites: Dict[str, _Site] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        name, prob, count, skip = raw, 1.0, None, 0
        # modifiers may appear in any order; the site name is the prefix up
        # to the first marker, then the modifier tail is walked char-wise
        first = min((i for i in (raw.find(m) for m in "@*+") if i >= 0),
                    default=-1)
        if first >= 0:
            name, tail = raw[:first], raw[first:]
            i = 0
            try:
                while i < len(tail):
                    marker = tail[i]
                    j = i + 1
                    while j < len(tail) and tail[j] not in "@*+":
                        j += 1
                    val = tail[i + 1:j]
                    if marker == "@":
                        prob = float(val)
                    elif marker == "*":
                        count = int(val)
                    elif marker == "+":
                        skip = int(val)
                    i = j
            except ValueError as e:
                raise ValueError(f"bad fault spec {raw!r}: {e}") from e
        if not name:
            raise ValueError(f"bad fault spec {raw!r}: empty site name")
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"bad fault spec {raw!r}: prob not in [0,1]")
        if (count is not None and count < 0) or skip < 0:
            raise ValueError(f"bad fault spec {raw!r}: negative count/skip")
        sites[name] = _Site(name, prob, count, skip, seed)
    return sites


# FaultMetrics (faults_injected_total{site}), wired by the node; None for
# library users — one None-check per FIRE, not per evaluation
metrics = None


def set_fault_metrics(m) -> None:
    global metrics
    metrics = m


class FaultPlane:
    """Singleton holding every armed site. Disabled == empty == free."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: Dict[str, _Site] = {}
        self._spec = ""
        self._seed = 0

    # -- configuration -----------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self._sites)

    @property
    def spec(self) -> str:
        return self._spec

    @property
    def seed(self) -> int:
        return self._seed

    def configure(self, spec: str, seed: int = 0) -> "FaultPlane":
        """Arm sites from a spec string (see module grammar). Replaces any
        previous configuration; returns self for chaining."""
        parsed = _parse_spec(spec, seed)
        with self._lock:
            self._sites = parsed
            self._spec = spec
            self._seed = seed
        return self

    def configure_from_env(self, environ=os.environ) -> "FaultPlane":
        spec = environ.get(ENV_SPEC, "")
        if spec:
            self.configure(spec, int(environ.get(ENV_SEED, "0") or "0"))
            unknown = {s for s in self._sites if not is_known_site(s)}
            if unknown:
                logger.warning(
                    "%s arms site(s) no production code consults: %s — "
                    "known sites: %s", ENV_SPEC, sorted(unknown),
                    sorted(KNOWN_SITES))
        return self

    def reset(self) -> None:
        """Disarm every site (test fixtures call this between tests)."""
        with self._lock:
            self._sites = {}
            self._spec = ""
            self._seed = 0

    # -- evaluation (the production seam) ----------------------------------

    def armed(self, site: str) -> bool:
        """Lock-free membership probe for hot paths that want to skip
        ``fire``'s lock when the site isn't configured at all. Safe:
        ``_sites`` is replaced wholesale under configure/reset, and a dict
        membership test is atomic under the GIL."""
        return site in self._sites

    def fire(self, site: str) -> bool:
        """Evaluate one trigger at `site`; True when the fault should
        happen. The disabled fast path is a single dict-truthiness check."""
        if not self._sites:
            return False
        with self._lock:
            st = self._sites.get(site)
            if st is None or not st.evaluate():
                return False
        m = metrics
        if m is not None:
            m.faults_injected_total.labels(site).inc()
        return True

    def inject(self, site: str,
               exc_factory: Optional[Callable[[str], BaseException]] = None
               ) -> None:
        """Raise at `site` when armed; no-op otherwise. ``exc_factory``
        builds the exception (default: InjectedFault) so storage sites can
        surface an OSError exactly like the real failure would."""
        if self.fire(site):
            raise (exc_factory(site) if exc_factory is not None
                   else InjectedFault(site))

    def mutate(self, site: str, data: bytes) -> bytes:
        """Content-corruption seam: return `data` with one
        deterministically-chosen bit flipped when `site` fires, `data`
        unchanged otherwise. The flip position comes from the site's own
        seeded RNG, so a corruption schedule replays exactly — the i-th
        fire of a site always tampers the same way. Empty payloads pass
        through untouched (there is nothing to lie about)."""
        if not self._sites or not data:
            return data
        with self._lock:
            st = self._sites.get(site)
            if st is None or not st.evaluate():
                return data
            # draw under the lock from the site stream: position/bit are
            # part of the deterministic schedule, not scheduling noise
            pos = st.rng.randrange(len(data))
            bit = 1 << st.rng.randrange(8)
        m = metrics
        if m is not None:
            m.faults_injected_total.labels(site).inc()
        out = bytearray(data)
        out[pos] ^= bit
        return bytes(out)

    def tear(self, site: str, data: bytes) -> bytes:
        """Torn-write seam: when `site` fires, return `data` truncated at a
        seeded prefix (0 <= cut < len — always strictly partial) with, on a
        seeded coin flip, a short garbage suffix appended (the disk sector
        half-written at crash time). `data` unchanged otherwise. Both draws
        come from the site's own stream, so the i-th tear of a site is the
        same tear every run — a torn-tail repro replays from its seed.
        Empty payloads pass through (nothing to tear)."""
        if not self._sites or not data:
            return data
        with self._lock:
            st = self._sites.get(site)
            if st is None or not st.evaluate():
                return data
            cut = st.rng.randrange(len(data))
            garbage = b""
            if st.rng.random() < 0.5:
                garbage = st.rng.randbytes(st.rng.randrange(1, 9))
        m = metrics
        if m is not None:
            m.faults_injected_total.labels(site).inc()
        return data[:cut] + garbage

    def tear_index(self, site: str, n: int) -> Optional[int]:
        """Batch-level tear: when `site` fires, a seeded cut index in
        [0, n) — the caller applies only items[:cut] before failing, the
        multi-record analog of a byte-level torn write (used by the KV
        write-batch seam, where the unit of emission is a record, not a
        byte). None when the site is quiet."""
        if not self._sites or n <= 0:
            return None
        with self._lock:
            st = self._sites.get(site)
            if st is None or not st.evaluate():
                return None
            cut = st.rng.randrange(n)
        m = metrics
        if m is not None:
            m.faults_injected_total.labels(site).inc()
        return cut

    def skew_ns(self, site: str, ident: str,
                max_abs_ns: int = 500_000_000) -> int:
        """Value-returning seam for clock-skew sites: a deterministic
        signed offset in [-max_abs_ns, +max_abs_ns] nanoseconds for
        ``ident`` (node name / validator address) when ``site`` is armed,
        0 otherwise. The offset is a pure function of (seed, site, ident)
        — NOT of the site's RNG stream position — so every consultation
        returns the same value and arming order can't perturb it; two
        nodes with different idents get different (but each deterministic)
        offsets from one spec. The ``@prob`` modifier scales the magnitude
        window (``clock.skew@0.5`` draws from ±max/2) rather than gating
        firing — a clock is skewed or it isn't, per process."""
        if not self._sites:
            return 0
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return 0
            st.evals += 1
            span = int(max_abs_ns * st.prob)
            seed = self._seed
            if span <= 0:
                return 0
            st.fires += 1
        m = metrics
        if m is not None:
            m.faults_injected_total.labels(site).inc()
        rng = random.Random(zlib.crc32(f"{seed}|{site}|{ident}".encode()))
        return rng.randint(-span, span)

    # -- introspection (tests / tools) -------------------------------------

    def counts(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {name: {"evals": s.evals, "fires": s.fires}
                    for name, s in self._sites.items()}

    def fires(self, site: str) -> int:
        with self._lock:
            s = self._sites.get(site)
            return s.fires if s is not None else 0


#: process-wide singleton; armed from the environment at import so
#: subprocess nodes (e2e runner, cmd start) inherit TMTPU_FAULTS for free
faults = FaultPlane().configure_from_env()
