"""Per-tx lifecycle tracker: the ingestion-plane observability spine.

The ROADMAP's ingestion plane ("mempool + RPC built for millions of users")
cannot be built — or judged — without per-tx end-to-end measurement: runtimes
chasing sub-second finality treat broadcast→commit latency percentiles as the
first-class product metric (ACE Runtime, arXiv 2603.10242), and
committee-consensus evaluations are throughput/latency trade curves
(arXiv 2302.00418). This module records that trade curve's raw material
live, per sampled tx, as monotonic stage stamps:

    rpc_received        the tx arrived at a broadcast_tx_* RPC handler
    preverified         the ingestion plane's batched (or scalar)
                        signature pre-verification verdict landed
                        (outcome accepted|rejected; rejected is
                        terminal — an invalid signature never reaches
                        the app)
    checktx_done        the app's CheckTx verdict landed (outcome
                        accepted|rejected; rejected is terminal)
    mempool_admitted    the tx entered the mempool
    first_gossip        we first forwarded the tx to any peer
    proposal_included   the tx landed in a proposal block (proposer stamps
                        at creation; followers at complete-proposal decode)
    committed           the tx's block committed (terminal)
    rechecked           post-block CheckTx re-run while still pending
                        (repeatable; outcome rejected is terminal)

Design mirrors ``crypto/phases.py`` / ``consensus/timeline.py``:

* **hash-sampled**: a tx participates iff the leading 8 bytes of its
  sha256 key fall under the sample rate (``TMTPU_TXLIFE_SAMPLE``, default
  1.0) — deterministic per tx, so every node in a fleet samples the SAME
  txs and ``tools/trace_merge.py`` can correlate one tx across nodes;
* **bounded**: sealed records land on a ring (default 512) and the
  in-flight map is capped (default 4096, oldest evicted as ``lost``) so a
  million-user firehose cannot grow process memory;
* **cheap when idle**: one attribute load + dict lookup per mark for
  unsampled txs; trackers are per-node instances (the in-proc test nets
  run 4 nodes in one process), wired once onto ``CListMempool.txlife``
  and reached by the RPC layer / consensus hooks through the mempool.

On seal the tracker:

* observes ``tendermint_mempool_tx_stage_seconds{stage}`` (interval from
  the previous stamped stage) and, for committed txs,
  ``tendermint_mempool_tx_commit_latency_seconds`` (first stamp →
  committed: on the RPC node that is the honest broadcast→commit number,
  on gossip-fed peers it runs from ``checktx_done``);
* emits height-tagged ``tx_<stage>`` tracer spans on a synthetic
  per-record track, so a merged Perfetto view shows tx latency riding
  next to the PR 6 consensus stage timeline;
* appends a JSON-safe record served at ``GET /tx_timeline?limit=N`` and
  bundled by debugdump as ``txlife.json``.
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from .trace import tracer

#: canonical stage order (README "Ingestion observability"); durations are
#: deltas between consecutive STAMPED stages in this order
STAGES = ("rpc_received", "preverified", "checktx_done", "mempool_admitted",
          "first_gossip", "proposal_included", "committed", "rechecked")

#: stages allowed to OPEN a record — everything else on an unknown key is
#: a stale mark (e.g. a block commit for a tx sampled before a restart).
#: Gossip-fed txs skip the RPC door AND the ingest pipeline, so both
#: preverified and checktx_done can open a record.
ENTRY_STAGES = ("rpc_received", "preverified", "checktx_done")

DEFAULT_RING_CAPACITY = 512
DEFAULT_ACTIVE_CAPACITY = 4096

#: marks kept per record: ``rechecked`` repeats every block a tx stays
#: pending, and an unbounded marks list would grow the active map's
#: records without bound — the recheck COUNT keeps counting past the cap
MAX_MARKS_PER_RECORD = 64

#: synthetic tracer track base for per-tx spans (same trick as
#: crypto/phases.py segment tracks): concurrent tx lifecycles overlap in
#: wall time and would render mis-nested on one shared track
_TX_TRACK_BASE = 0x71F0000
_TRACK_SEQ = itertools.count()


def _env_sample_rate() -> float:
    try:
        return min(1.0, max(0.0, float(
            os.environ.get("TMTPU_TXLIFE_SAMPLE", "1.0"))))
    except ValueError:
        return 1.0


class TxLifecycle:
    """One node's tx-lifecycle recorder. All methods are thread-safe: RPC
    handlers run on the event loop thread, ``CheckTx`` under the mempool
    lock, commits on the consensus loop."""

    def __init__(self, sample_rate: Optional[float] = None,
                 ring_capacity: int = DEFAULT_RING_CAPACITY,
                 active_capacity: int = DEFAULT_ACTIVE_CAPACITY):
        self.sample_rate = (_env_sample_rate() if sample_rate is None
                            else min(1.0, max(0.0, float(sample_rate))))
        self.ring_capacity = ring_capacity
        self.active_capacity = active_capacity
        self.enabled = True
        self.metrics = None  # MempoolMetrics, wired by the node
        self._lock = threading.Lock()
        self._active: "collections.OrderedDict[bytes, dict]" = \
            collections.OrderedDict()
        self._ring: "collections.deque" = collections.deque(
            maxlen=ring_capacity)
        self.sealed_total = 0
        self.evicted_total = 0  # active-map overflow (records closed "lost")

    # -- sampling ----------------------------------------------------------

    def sampled(self, key: bytes) -> bool:
        """Deterministic by tx hash: the leading 64 bits of the sha256 key
        as a fraction of 2^64. Every node samples the same txs."""
        if not self.enabled or self.sample_rate <= 0.0:
            return False
        if self.sample_rate >= 1.0:
            return True
        return int.from_bytes(key[:8], "big") < self.sample_rate * 2.0 ** 64

    # -- recording ---------------------------------------------------------

    def mark(self, key: bytes, stage: str, height: Optional[int] = None,
             outcome: Optional[str] = None) -> None:
        """Stamp ``stage`` for the tx with sha256 digest ``key``. First
        stamp per stage wins (``rechecked`` repeats and counts);
        ``committed`` — and any stage with ``outcome="rejected"`` — seals
        the record. Unknown keys only open a record at an entry stage."""
        if not self.sampled(key):
            # the cheap-when-idle contract: an unsampled tx (deterministic
            # per key, so it can never be in the active map) pays no clock
            # read and never touches the tracker lock — the RPC loop, the
            # mempool mutex holder, and the consensus loop must not
            # contend here at low sample rates
            return
        t_wall, t_perf = time.time(), time.perf_counter()
        with self._lock:
            rec = self._active.get(key)
            if rec is None:
                if stage not in ENTRY_STAGES:
                    return
                rec = {
                    "key": key.hex(),
                    "t0_wall": t_wall,
                    "t0_perf": t_perf,
                    "height": None,
                    "marks": [],        # (stage, t_wall, t_perf) in order
                    "_by_stage": {},    # stage -> t_perf, first wins
                    "rechecks": 0,
                    "terminal": None,
                }
                self._active[key] = rec
                if len(self._active) > self.active_capacity:
                    _, lost = self._active.popitem(last=False)
                    lost["terminal"] = "lost"
                    self._ring.append(self._seal_view(lost))
                    self.evicted_total += 1
            if stage == "rechecked":
                rec["rechecks"] += 1
            elif stage in rec["_by_stage"]:
                return  # first stamp wins; a duplicate is not a new event
            # every non-repeating stage appends at most once; only the
            # repeating rechecked marks are capped (the count keeps going)
            if stage != "rechecked" or rec["rechecks"] <= MAX_MARKS_PER_RECORD:
                rec["marks"].append((stage, t_wall, t_perf))
            rec["_by_stage"].setdefault(stage, t_perf)
            if height is not None:
                rec["height"] = int(height)
            terminal = (stage == "committed"
                        or (outcome == "rejected"
                            and stage in ("preverified", "checktx_done",
                                          "rechecked")))
            if not terminal:
                return
            rec["terminal"] = ("committed" if stage == "committed"
                               else "rejected")
            self._active.pop(key, None)
            view = self._seal_view(rec)
            self._ring.append(view)
            self.sealed_total += 1
        # metrics + tracer OUTSIDE the lock: observing takes metric locks
        # and the tracer ring lock — neither belongs under ours
        self._observe(rec, view)

    def discard_phantom(self, key: bytes) -> None:
        """Drop an active record that never got past the front door
        (``rpc_received``/``preverified``): a client retrying an
        already-committed (cache-blocked) tx opens a record — and with
        the ingest pipeline in front, collects a preverified stamp —
        that no later stage will ever close. Under a retry storm those
        phantoms would evict genuine in-flight records and flood the
        sealed ring with ``lost`` entries. A record with any admission
        stamp (checktx_done onward) is left alone (the live original of
        a duplicate broadcast)."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._active.get(key)
            if rec is not None and \
                    set(rec["_by_stage"]) <= {"rpc_received", "preverified"}:
                self._active.pop(key, None)

    def tracking(self) -> bool:
        """False when nothing can ever be recorded (disabled or rate 0) —
        the guard per-block hook loops check before hashing anything."""
        return self.enabled and self.sample_rate > 0.0

    def mark_tx(self, tx: bytes, stage: str, height: Optional[int] = None,
                outcome: Optional[str] = None) -> None:
        """``mark`` for call sites that hold the raw tx, not its digest
        (proposal/commit hooks walking ``block.data.txs``). A rate-0
        tracker pays no sha256: sampling is key-independent then."""
        if not self.tracking():
            return
        self.mark(hashlib.sha256(tx).digest(), stage, height=height,
                  outcome=outcome)

    # -- seal side effects -------------------------------------------------

    def _seal_view(self, rec: dict) -> dict:
        durations: Dict[str, float] = {}
        prev = rec["t0_perf"]
        for stage in STAGES:
            got = rec["_by_stage"].get(stage)
            if got is None:
                continue
            durations[stage] = max(0.0, got - prev)
            prev = max(prev, got)
        view = {
            "key": rec["key"],
            "t0_wall": rec["t0_wall"],
            "height": rec["height"],
            "terminal": rec["terminal"],
            "rechecks": rec["rechecks"],
            "marks": [[stage, t_wall] for stage, t_wall, _ in rec["marks"]],
            "durations": {s: round(d, 6) for s, d in durations.items()},
            "total_s": round(max(0.0, prev - rec["t0_perf"]), 6),
        }
        rec["_durations"] = durations
        return view

    def _observe(self, rec: dict, view: dict) -> None:
        m = self.metrics
        if m is not None:
            try:
                for stage, d in rec["_durations"].items():
                    m.tx_stage_seconds.labels(stage).observe(d)
                if rec["terminal"] == "committed":
                    m.tx_commit_latency_seconds.observe(
                        max(0.0, rec["_by_stage"]["committed"]
                            - rec["t0_perf"]))
            except Exception:
                pass
        if tracer.enabled:
            tid = _TX_TRACK_BASE + (next(_TRACK_SEQ) & 0xFFF)
            args = {"tx": rec["key"][:16], "terminal": rec["terminal"]}
            if rec["height"] is not None:
                args["height"] = rec["height"]
            prev = rec["t0_perf"]
            for stage in STAGES:
                got = rec["_by_stage"].get(stage)
                if got is None:
                    continue
                start = min(prev, got)
                tracer.complete(f"tx_{stage}", start * 1e6,
                                max(0.0, got - start) * 1e6, tid=tid, **args)
                prev = max(prev, got)

    # -- read side (RPC /tx_timeline, debugdump txlife.json) ---------------

    def tail(self, n: int) -> List[dict]:
        with self._lock:
            records = list(self._ring)
        return records[-n:] if n < len(records) else records

    def snapshot(self, limit: int = 20) -> dict:
        with self._lock:
            active = len(self._active)
        return {
            "enabled": self.enabled,
            "sample_rate": self.sample_rate,
            "ring_capacity": self.ring_capacity,
            "active_capacity": self.active_capacity,
            "active": active,
            "sealed_total": self.sealed_total,
            "evicted_total": self.evicted_total,
            "records": self.tail(max(1, int(limit))),
        }

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._ring.clear()
            self.sealed_total = 0
            self.evicted_total = 0
