"""Dependency-free span tracer with Chrome trace-event export.

The reference leans on Go's pprof/runtime-trace for hot-path attribution;
Python has no equivalent that survives a wedged loop AND is cheap enough to
leave compiled into consensus-critical code. This is the minimal analog:

    from tendermint_tpu.libs.trace import tracer
    with tracer.span("verify_window", height=h, n_sigs=n):
        ...

records one complete ("X"-phase) Chrome trace event per span onto a bounded,
thread-safe ring buffer. ``tracer.chrome_trace()`` / ``tracer.write(path)``
export the standard trace-event JSON that https://ui.perfetto.dev and
chrome://tracing load directly.

Disabled (the default) the hot path pays one attribute check: call sites
guard with ``if tracer.enabled`` or rely on :meth:`Tracer.span` returning a
shared no-op context manager — no event dict, no span object, no timestamp
read is allocated. ``bench.py --trace-out`` and tests enable it explicitly.

The ring is a ``collections.deque(maxlen=...)``: appends are atomic under
the GIL and old events fall off the front, so a long-running node can keep
the tracer on and still bound memory — the dump (libs/debugdump.py) snapshots
the tail of whatever survived.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 65536


class _NoopSpan:
    """Shared do-nothing context manager: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **args) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[Dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def set(self, **args) -> "_Span":
        """Amend the span's args mid-body (e.g. the route actually taken
        when a device attempt fell back to host)."""
        if self._args is None:
            self._args = {}
        self._args.update(args)
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        ev = {
            "name": self._name,
            "ph": "X",
            "ts": self._t0 * 1e6,  # trace-event timestamps are microseconds
            "dur": (t1 - self._t0) * 1e6,
            "pid": _PID,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if self._args:
            ev["args"] = self._args
        self._tracer._buf.append(ev)


_PID = os.getpid()


class Tracer:
    """Bounded ring of Chrome trace events; safe to share across threads."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = False):
        self.capacity = capacity
        self.enabled = enabled
        self._buf: "collections.deque" = collections.deque(maxlen=capacity)

    # -- control -------------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._buf.clear()

    # -- recording -----------------------------------------------------------

    def span(self, name: str, **args) -> object:
        """Context manager timing its body as one complete trace event.
        When disabled, returns a shared no-op — nothing is allocated."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker ("i"-phase instant event)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.perf_counter() * 1e6, "pid": _PID,
              "tid": threading.get_ident() & 0x7FFFFFFF}
        if args:
            ev["args"] = args
        self._buf.append(ev)

    # -- export --------------------------------------------------------------

    def events(self) -> List[dict]:
        return list(self._buf)

    def tail(self, n: int) -> List[dict]:
        buf = self._buf
        if n >= len(buf):
            return list(buf)
        return list(buf)[-n:]

    def chrome_trace(self) -> dict:
        """The standard trace-event container Perfetto/chrome://tracing
        load: {"traceEvents": [...], "displayTimeUnit": "ms"}."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


#: process-global tracer, disabled by default; instrumented hot paths check
#: ``tracer.enabled`` (one attribute load) before doing any tracing work
tracer = Tracer()
