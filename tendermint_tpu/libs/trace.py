"""Dependency-free span tracer with Chrome trace-event export.

The reference leans on Go's pprof/runtime-trace for hot-path attribution;
Python has no equivalent that survives a wedged loop AND is cheap enough to
leave compiled into consensus-critical code. This is the minimal analog:

    from tendermint_tpu.libs.trace import tracer
    with tracer.span("verify_window", height=h, n_sigs=n):
        ...

records one complete ("X"-phase) Chrome trace event per span onto a bounded,
thread-safe ring buffer. ``tracer.chrome_trace()`` / ``tracer.write(path)``
export the standard trace-event JSON that https://ui.perfetto.dev and
chrome://tracing load directly.

Disabled (the default) the hot path pays one attribute check: call sites
guard with ``if tracer.enabled`` or rely on :meth:`Tracer.span` returning a
shared no-op context manager — no event dict, no span object, no timestamp
read is allocated. ``bench.py --trace-out`` and tests enable it explicitly.

The ring is a ``collections.deque(maxlen=...)``: appends are atomic under
the GIL and old events fall off the front, so a long-running node can keep
the tracer on and still bound memory — the dump (libs/debugdump.py) snapshots
the tail of whatever survived.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 65536


class _NoopSpan:
    """Shared do-nothing context manager: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set(self, **args) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[Dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def set(self, **args) -> "_Span":
        """Amend the span's args mid-body (e.g. the route actually taken
        when a device attempt fell back to host)."""
        if self._args is None:
            self._args = {}
        self._args.update(args)
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        ev = {
            "name": self._name,
            "ph": "X",
            "ts": self._t0 * 1e6,  # trace-event timestamps are microseconds
            "dur": (t1 - self._t0) * 1e6,
            "pid": _PID,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if self._args:
            ev["args"] = self._args
        self._tracer._record(ev)


_PID = os.getpid()


class Tracer:
    """Bounded ring of Chrome trace events; safe to share across threads."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = False):
        self.capacity = capacity
        self.enabled = enabled
        self._buf: "collections.deque" = collections.deque(maxlen=capacity)
        #: events pushed off the full ring (saturation visibility: a trace
        #: whose front was eaten should SAY so, not just look short)
        self.dropped = 0
        #: optional Counter (NodeMetrics.trace_dropped_events_total) so the
        #: saturation shows up on /metrics, not only in the export header
        self.drop_counter = None
        #: cross-node correlation identity (set_identity): who produced this
        #: trace, and how its perf_counter timeline maps onto wall clock
        self.node_id: Optional[str] = None
        self.epoch_unix_s: Optional[float] = None
        self.epoch_perf_us: Optional[float] = None

    # -- control -------------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._buf.clear()
        self.dropped = 0

    def set_identity(self, node_id: str) -> None:
        """Stamp this process's trace with a node id and a wall↔perf epoch
        pair. ``ts`` fields stay in the process-local perf_counter domain;
        the export header carries (epoch_unix_s, epoch_perf_us) sampled at
        the same instant, so tools/trace_merge.py can re-base N nodes'
        events onto the shared wall clock and align their tracks."""
        self.node_id = str(node_id)
        self.epoch_unix_s = time.time()
        self.epoch_perf_us = time.perf_counter() * 1e6

    # -- recording -----------------------------------------------------------

    def _record(self, ev: dict) -> None:
        buf = self._buf
        if len(buf) == buf.maxlen:
            self.dropped += 1
            c = self.drop_counter
            if c is not None:
                try:
                    c.inc()
                except Exception:
                    pass
        buf.append(ev)

    def span(self, name: str, **args) -> object:
        """Context manager timing its body as one complete trace event.
        When disabled, returns a shared no-op — nothing is allocated."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker ("i"-phase instant event)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.perf_counter() * 1e6, "pid": _PID,
              "tid": threading.get_ident() & 0x7FFFFFFF}
        if args:
            ev["args"] = args
        self._record(ev)

    def complete(self, name: str, ts_us: float, dur_us: float,
                 tid: Optional[int] = None, **args) -> None:
        """Record a complete event with an EXPLICIT start/duration (both in
        perf_counter microseconds) — for retroactive spans whose endpoints
        were sampled outside a context manager (the consensus stage
        timeline seals a height and emits one span per stage interval).
        ``tid`` overrides the emitting thread's id: retroactive spans for
        work that ran elsewhere (a pipeline slot's pack on a worker) would
        otherwise render overlapping slices on the emitter's track."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
              "pid": _PID,
              "tid": (tid if tid is not None
                      else threading.get_ident() & 0x7FFFFFFF)}
        if args:
            ev["args"] = args
        self._record(ev)

    # -- export --------------------------------------------------------------

    def events(self) -> List[dict]:
        return list(self._buf)

    def tail(self, n: int) -> List[dict]:
        buf = self._buf
        if n >= len(buf):
            return list(buf)
        return list(buf)[-n:]

    def chrome_trace(self, events: Optional[list] = None) -> dict:
        """The standard trace-event container Perfetto/chrome://tracing
        load: {"traceEvents": [...], "displayTimeUnit": "ms"} — plus the
        correlation header (node_id + wall↔perf epoch, set_identity) and a
        ``dropped`` count so a saturated ring is visible instead of a
        silently truncated trace. Viewers ignore the extra keys. Pass
        ``events`` to wrap a subset (debugdump's tail) in the same
        header instead of the full ring."""
        if events is None:
            events = self.events()
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "dropped": self.dropped}
        if self.node_id is not None:
            # Perfetto names the pid track from this metadata event
            doc["traceEvents"] = [{
                "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
                "args": {"name": self.node_id}}] + events
            doc["node_id"] = self.node_id
            doc["epoch_unix_s"] = self.epoch_unix_s
            doc["epoch_perf_us"] = self.epoch_perf_us
        return doc

    def write(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


#: process-global tracer, disabled by default; instrumented hot paths check
#: ``tracer.enabled`` (one attribute load) before doing any tracing work
tracer = Tracer()
