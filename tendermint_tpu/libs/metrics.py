"""Prometheus-style metrics, dependency-free
(reference per-module metrics.go + prometheus/client_golang).

Counter / Gauge / Histogram with labels, collected in a Registry that
renders the text exposition format served on the node's
``instrumentation.prometheus_listen_addr`` /metrics endpoint
(reference node/node.go:962).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str) -> "_Bound":
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} "
                             f"labels, got {len(values)}")
        return _Bound(self, tuple(str(v) for v in values))

    def _fmt_labels(self, lv: Tuple[str, ...]) -> str:
        if not lv:
            return ""
        # sorted by label name — the SAME ordering Histogram bucket lines
        # use, so one metric's series never mix two orderings and raw-text
        # diffs/greps are deterministic (client_golang sorts identically)
        inner = ",".join(f'{k}="{_escape_label(v)}"'
                         for k, v in sorted(zip(self.label_names, lv)))
        return "{" + inner + "}"

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._values.items())
        for lv, val in items:
            out.append(f"{self.name}{self._fmt_labels(lv)} {_fmt(val)}")
        return out

    def _check_arity(self, labels: Tuple) -> Tuple[str, ...]:
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} "
                             f"labels, got {len(labels)}")
        return tuple(str(v) for v in labels)

    def value(self, *labels: str) -> float:
        """Current value for a counter/gauge label set (0.0 if never
        touched) — the seam bench/debug tooling reads instead of parsing
        the exposition text."""
        lv = self._check_arity(labels)
        with self._lock:
            return self._values.get(lv, 0.0)


class _Bound:
    __slots__ = ("metric", "lv")

    def __init__(self, metric: "_Metric", lv: Tuple[str, ...]):
        self.metric = metric
        self.lv = lv

    def inc(self, amount: float = 1.0) -> None:
        self.metric._inc(self.lv, amount)

    def set(self, value: float) -> None:
        self.metric._set(self.lv, value)

    def observe(self, value: float) -> None:
        self.metric._observe(self.lv, value)


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _escape_label(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the series line is unparseable
    (exposition format spec, "Line format")."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _inc(self, lv: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[lv] = self._values.get(lv, 0.0) + amount

    def _set(self, lv, value):  # misuse guard
        raise TypeError("counters only go up")

    def _observe(self, lv, value):  # misuse guard
        raise TypeError(f"{self.name}: observe() is only valid on histograms")


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        self._set((), value)

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _set(self, lv: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._values[lv] = float(value)

    def _inc(self, lv: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[lv] = self._values.get(lv, 0.0) + amount

    def _observe(self, lv, value):  # misuse guard
        raise TypeError(f"{self.name}: observe() is only valid on histograms")


class Histogram(_Metric):
    kind = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float) -> None:
        self._observe((), value)

    def _observe(self, lv: Tuple[str, ...], value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(lv, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[lv] = self._sums.get(lv, 0.0) + value
            self._totals[lv] = self._totals.get(lv, 0) + 1

    def _set(self, lv, value):  # misuse guard
        raise TypeError(f"{self.name}: set() is not valid on histograms")

    def _inc(self, lv, amount):  # misuse guard
        raise TypeError(f"{self.name}: inc() is not valid on histograms")

    def value(self, *labels):  # misuse guard: _values is never populated
        raise TypeError(f"{self.name}: histograms have no single value — "
                        "use sum_value()/count_value()")

    def sum_value(self, *labels: str) -> float:
        lv = self._check_arity(labels)
        with self._lock:
            return self._sums.get(lv, 0.0)

    def count_value(self, *labels: str) -> int:
        lv = self._check_arity(labels)
        with self._lock:
            return self._totals.get(lv, 0)

    def _bucket_labels(self, lv: Tuple[str, ...], le: str) -> str:
        # deterministic: label names sorted, `le` always last (Prometheus
        # only requires consistency, but scrapers and tests diff raw text)
        pairs = sorted(zip(self.label_names, lv))
        pairs.append(("le", le))
        return ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            items = sorted(self._counts.items())
            for lv, counts in items:
                for b, c in zip(self.buckets, counts):
                    inner = self._bucket_labels(lv, _fmt(b))
                    out.append(f"{self.name}_bucket{{{inner}}} {c}")
                inner = self._bucket_labels(lv, "+Inf")
                out.append(f"{self.name}_bucket{{{inner}}} {self._totals[lv]}")
                out.append(f"{self.name}_sum{self._fmt_labels(lv)} "
                           f"{_fmt(self._sums[lv])}")
                out.append(f"{self.name}_count{self._fmt_labels(lv)} "
                           f"{self._totals[lv]}")
        return out


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._names: set = set()
        self._lock = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._add(Counter(self._fq(subsystem, name), help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._add(Gauge(self._fq(subsystem, name), help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str,
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._add(Histogram(self._fq(subsystem, name), help_, labels,
                                   buckets))

    def _fq(self, subsystem: str, name: str) -> str:
        parts = [p for p in (self.namespace, subsystem, name) if p]
        return "_".join(parts)

    def _add(self, m):
        with self._lock:
            if m.name in self._names:
                # a silent duplicate double-renders the series and Prometheus
                # rejects the whole scrape — fail at registration instead
                raise ValueError(f"metric {m.name!r} already registered")
            self._names.add(m.name)
            self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# --- per-module metric sets (reference consensus/metrics.go etc.) -----------

class ConsensusMetrics:
    """(consensus/metrics.go — the load-bearing subset of its 23 series)"""

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.height = g("consensus", "height", "Height of the chain.")
        self.rounds = g("consensus", "rounds", "Round of the chain.")
        self.validators = g("consensus", "validators",
                            "Number of validators.")
        self.validators_power = g("consensus", "validators_power",
                                  "Total voting power of validators.")
        self.missing_validators = g("consensus", "missing_validators",
                                    "Validators missing from the last commit.")
        self.byzantine_validators = g("consensus", "byzantine_validators",
                                      "Validators that equivocated.")
        self.num_txs = g("consensus", "num_txs", "Txs in the latest block.")
        self.block_size_bytes = g("consensus", "block_size_bytes",
                                  "Size of the latest block.")
        self.total_txs = c("consensus", "total_txs", "Total committed txs.")
        self.block_interval_seconds = h(
            "consensus", "block_interval_seconds",
            "Time between this and the last block.")
        self.fast_syncing = g("consensus", "fast_syncing",
                              "Whether the node is fast syncing.")
        self.block_parts = c("consensus", "block_parts",
                             "Block parts transmitted per peer.", ["peer_id"])
        self.quorum_prevote_delay = h(
            "consensus", "quorum_prevote_delay",
            "Seconds from proposal timestamp to 2/3 prevotes.")
        self.missing_validators_power = g(
            "consensus", "missing_validators_power",
            "Voting power of validators missing from the last commit.")
        self.byzantine_validators_power = g(
            "consensus", "byzantine_validators_power",
            "Voting power of validators that equivocated.")
        self.validator_power = g(
            "consensus", "validator_power",
            "This node's voting power (0 when not a validator).")
        self.validator_last_signed_height = g(
            "consensus", "validator_last_signed_height",
            "Last height this node's validator signed.")
        self.validator_missed_blocks = c(
            "consensus", "validator_missed_blocks",
            "Blocks this node's validator missed signing.")
        self.committed_height = g(
            "consensus", "committed_height", "Latest committed height.")
        self.state_syncing = g(
            "consensus", "state_syncing",
            "Whether the node is state syncing.")
        self.proposal_receive_count = c(
            "consensus", "proposal_receive_count",
            "Proposals received.", ["status"])
        self.latest_block_height = g(
            "consensus", "latest_block_height",
            "Alias of committed height for dashboards.")
        # -- live consensus plane (event-driven gossip + WAL group commit) --
        self.gossip_wakeups_total = c(
            "consensus", "gossip_wakeups_total",
            "Gossip iterations triggered by an event wakeup.", ["routine"])
        self.gossip_polls_total = c(
            "consensus", "gossip_polls_total",
            "Gossip iterations triggered by the fallback sleep cap.",
            ["routine"])
        self.encode_cache_hits_total = c(
            "consensus", "encode_cache_hits_total",
            "Wire-encode cache hits (one encode served many sends).",
            ["kind"])
        self.encode_cache_misses_total = c(
            "consensus", "encode_cache_misses_total",
            "Wire-encode cache misses (message encoded fresh).", ["kind"])
        self.wal_fsyncs_total = c(
            "consensus", "wal_fsyncs_total", "WAL fsync calls.")
        self.wal_records_per_fsync = h(
            "consensus", "wal_records_per_fsync",
            "WAL records made durable by each fsync (group-commit batch).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self.wal_fsync_seconds = h(
            "consensus", "wal_fsync_seconds", "WAL fsync latency.",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1))
        # -- robustness plane (fault injection / watchdog) ---------------
        self.wal_fsync_errors_total = c(
            "consensus", "wal_fsync_errors_total",
            "WAL fsync calls that failed (fatal per fsync_error_policy).")
        # attribute keeps the catalog name; the series is
        # tendermint_consensus_stalled_total (subsystem supplies the prefix)
        self.consensus_stalled_total = c(
            "consensus", "stalled_total",
            "Stall episodes: no committed-height advance for "
            "stall_watchdog_s.")
        self.gossip_peer_refreshes_total = c(
            "consensus", "gossip_peer_refreshes_total",
            "Silent-peer delivery bitmaps cleared for re-gossip "
            "(gossip_stall_refresh_s).")
        # -- observability plane (consensus/timeline.py stage timeline) --
        # series tendermint_consensus_stage_seconds{stage=...}: per-height
        # interval from the previous stage mark to this one, observed when
        # the height seals at commit — the per-phase latency decomposition
        # of the consensus round (arXiv 2302.00418 / 2410.03347 attribute
        # wins exactly this way)
        self.stage_seconds = h(
            "consensus", "stage_seconds",
            "Seconds from the previous consensus stage mark to this one "
            "(proposal_received, prevote_sent, prevote_quorum, "
            "precommit_sent, precommit_quorum, commit_finalized).",
            ["stage"],
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0))
        # -- degraded-network plane (round churn under WAN/gray/asym) ----
        # reasons: timeout_propose / timeout_prevote (timeout-driven step
        # escalations that put the round on the nil-vote path),
        # timeout_precommit (the round actually advances), polka_skip
        # (2/3-any votes seen at a higher round jump us forward)
        self.round_advances_total = c(
            "consensus", "round_advances_total",
            "Round-escalation events by cause (timeout_propose, "
            "timeout_prevote, timeout_precommit, polka_skip).", ["reason"])
        self.rounds_per_height = h(
            "consensus", "rounds_per_height",
            "Rounds a height took to commit (1 = no escalation).",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32))


class MempoolMetrics:
    """(mempool/metrics.go — grown the ingestion-plane series a
    high-traffic mempool needs: depth in txs AND bytes on every mutation
    path, admission/rejection/eviction taxonomies, CheckTx/recheck
    latency distributions, and the per-tx lifecycle histograms fed by
    libs/txlife.py)."""

    #: CheckTx is an in-proc app call (~us) but socket/grpc apps reach ms
    CHECKTX_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25)
    #: broadcast→commit spans one to several block intervals
    COMMIT_LATENCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                              30.0, 60.0)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.size = g("mempool", "size", "Number of uncommitted txs.")
        self.size_bytes = g("mempool", "size_bytes",
                            "Total bytes of uncommitted txs (depth-bytes).")
        self.tx_size_bytes = h(
            "mempool", "tx_size_bytes", "Tx sizes in bytes.",
            buckets=(32, 128, 512, 2048, 8192, 32768, 131072))
        self.failed_txs = c(
            "mempool", "failed_txs",
            "Txs rejected before admission, by reason "
            "(cache-dup, app-reject, full, too-large, invalid-sig, "
            "malformed-stx).", ["reason"])
        self.admitted_txs_total = c(
            "mempool", "admitted_txs_total",
            "Txs that passed CheckTx and entered the mempool.")
        self.evicted_txs_total = c(
            "mempool", "evicted_txs_total",
            "Admitted txs removed without committing, by reason "
            "(recheck-failed, flush, priority-evicted, ttl-expired).",
            ["reason"])
        # -- ingestion fast path (mempool/ingest.py) ---------------------
        self.shed_txs_total = c(
            "mempool", "shed_txs_total",
            "Txs refused by admission control before any verification "
            "or app work, by reason (queue-full, sender-rate, "
            "fee-floor).", ["reason"])
        self.intake_queue_depth = g(
            "mempool", "intake_queue_depth",
            "Ingest pipeline intake depth sampled at each micro-batch "
            "flush (bounded by mempool.ingest_queue_size).")
        self.preverified_txs_total = c(
            "mempool", "preverified_txs_total",
            "Signature pre-verification verdicts, by path/outcome "
            "(accepted/rejected via the batched pipeline, scalar for "
            "inline admissions).", ["outcome"])
        self.preverify_cache_hits_total = c(
            "mempool", "preverify_cache_hits_total",
            "Signature checks skipped because a cached pre-verification "
            "verdict stood, by consumer (batch, checktx, recheck — "
            "recheck hits are what keep commits from re-verification "
            "storms).", ["path"])
        self.preverify_latency_seconds = h(
            "mempool", "preverify_latency_seconds",
            "Wall seconds one micro-batch spent in signature "
            "pre-verification (host or device, routed by "
            "crypto.BatchVerifier).",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        self.recheck_times = c("mempool", "recheck_times",
                               "Times txs were rechecked.")
        self.checktx_latency_seconds = h(
            "mempool", "checktx_latency_seconds",
            "App CheckTx latency for first-time admission checks.",
            buckets=self.CHECKTX_BUCKETS)
        self.recheck_latency_seconds = h(
            "mempool", "recheck_latency_seconds",
            "App CheckTx latency for post-block rechecks.",
            buckets=self.CHECKTX_BUCKETS)
        # -- per-tx lifecycle (libs/txlife.py) ---------------------------
        self.tx_stage_seconds = h(
            "mempool", "tx_stage_seconds",
            "Seconds from the previous lifecycle stage stamp to this one "
            "(rpc_received, preverified, checktx_done, mempool_admitted, "
            "first_gossip, proposal_included, committed, rechecked).",
            ["stage"],
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0))
        self.tx_commit_latency_seconds = h(
            "mempool", "tx_commit_latency_seconds",
            "End-to-end seconds from a sampled tx's first lifecycle stamp "
            "(rpc_received on the ingesting node) to its block commit.",
            buckets=self.COMMIT_LATENCY_BUCKETS)


class P2PMetrics:
    """(p2p/metrics.go)"""

    def __init__(self, reg: Registry):
        self.peers = reg.gauge("p2p", "peers", "Connected peers.")
        self.peer_receive_bytes_total = reg.counter(
            "p2p", "peer_receive_bytes_total",
            "Bytes received per channel.", ["chID"])
        self.peer_send_bytes_total = reg.counter(
            "p2p", "peer_send_bytes_total",
            "Bytes sent per channel.", ["chID"])


class RPCMetrics:
    """The RPC front door (no reference analog — rpc/jsonrpc has no
    metrics.go; an ingestion plane for millions of users starts with
    knowing what each endpoint costs). Per-endpoint latency/outcome,
    in-flight pressure, websocket-subscriber count, and request/response
    size distributions, all served back over the same /metrics endpoint
    the fleet scraper rolls up."""

    LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
    SIZE_BUCKETS = (64, 256, 1024, 4096, 16384, 65536, 262144, 1048576)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.request_seconds = h(
            "rpc", "request_seconds",
            "RPC request latency per endpoint (outcome ok|error; unknown "
            "methods are bucketed under endpoint=\"unknown\" so scans "
            "cannot explode series cardinality).",
            ["endpoint", "outcome"], buckets=self.LATENCY_BUCKETS)
        self.requests_in_flight = g(
            "rpc", "requests_in_flight",
            "RPC requests currently being handled.")
        self.websocket_subscribers = g(
            "rpc", "websocket_subscribers",
            "Open /websocket connections.")
        self.request_size_bytes = h(
            "rpc", "request_size_bytes",
            "HTTP request body (POST) or path+query (GET) bytes.",
            buckets=self.SIZE_BUCKETS)
        self.response_size_bytes = h(
            "rpc", "response_size_bytes",
            "Serialized JSON response bytes.", buckets=self.SIZE_BUCKETS)
        self.ws_slow_consumer_evictions_total = c(
            "rpc", "ws_slow_consumer_evictions_total",
            "Websocket subscribers evicted because their bounded send "
            "queue overflowed (a stalled reader must never back up the "
            "event bus).")


class LightServeMetrics:
    """The light-client serving plane (light/serve.py): coalescer flush
    shape, header-cache effectiveness, and reason-labeled admission sheds
    for a population of thousands of concurrent light clients."""

    OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

    def __init__(self, reg: Registry):
        c, h = reg.counter, reg.histogram
        self.requests_total = c(
            "lightserve", "requests_total",
            "Serving requests per route.", ["route"])
        self.sheds_total = c(
            "lightserve", "sheds_total",
            "Admission sheds per reason (client-rate, banned, queue-full); "
            "every shed is an explicit RPC error, never a stall.",
            ["reason"])
        self.flushes_total = c(
            "lightserve", "flushes_total",
            "Coalescer flushes (one batched device call each).")
        self.flush_occupancy = h(
            "lightserve", "flush_occupancy",
            "Verify requests per coalescer flush.",
            buckets=self.OCCUPANCY_BUCKETS)
        self.verdict_cache_hits_total = c(
            "lightserve", "verdict_cache_hits_total",
            "Verify requests answered from the bounded verdict cache.")
        self.cache_hits_total = c(
            "lightserve", "cache_hits_total",
            "Header-cache hits on /light_header.")
        self.cache_misses_total = c(
            "lightserve", "cache_misses_total",
            "Header-cache misses on /light_header.")
        self.cache_prefetches_total = c(
            "lightserve", "cache_prefetches_total",
            "Bisection-skeleton heights prefetched and pinned.")
        self.client_bans_total = c(
            "lightserve", "client_bans_total",
            "Clients banned by the abuse scoreboard, per reason.",
            ["reason"])


class StateMetrics:
    """(state/metrics.go)"""

    def __init__(self, reg: Registry):
        self.block_processing_time = reg.histogram(
            "state", "block_processing_time",
            "Seconds in ApplyBlock.", buckets=(0.001, 0.005, 0.01, 0.025,
                                               0.05, 0.1, 0.25, 0.5, 1.0))
        # optimistic parallel execution plane (state/parallel.py)
        self.parallel_exec_blocks = reg.counter(
            "state", "parallel_exec_blocks_total",
            "Blocks executed via the optimistic parallel path.")
        self.parallel_exec_conflict_txs = reg.counter(
            "state", "parallel_exec_conflict_txs_total",
            "Txs serially re-executed after conflict validation.")
        self.parallel_exec_fallbacks = reg.counter(
            "state", "parallel_exec_fallbacks_total",
            "Blocks that fell back to the serial spec path.",
            labels=("reason",))


class CryptoMetrics:
    """The verification plane (no reference analog — the batched verifier
    is this build's defining feature, so its routing must be observable:
    batch-size and verify-latency distributions are the decisive tuning
    inputs for committee-based consensus [arXiv:2302.00418], and
    offload-vs-host routing counters the same for an offload engine
    [arXiv:2112.02229])."""

    #: batch sizes span 1 (evidence pairs) to 128k (10k-val windows)
    BATCH_BUCKETS = (1, 4, 16, 64, 256, 1024, 4096, 16384, 65536, 131072)
    LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.batch_size = h(
            "crypto", "batch_size",
            "Signatures per verification batch.", ["route", "plane"],
            buckets=self.BATCH_BUCKETS)
        self.verify_latency_seconds = h(
            "crypto", "verify_latency_seconds",
            "End-to-end batch verification latency.", ["route", "plane"],
            buckets=self.LATENCY_BUCKETS)
        self.routing_decisions_total = c(
            "crypto", "routing_decisions_total",
            "Batches routed per backend.", ["route", "plane"])
        self.device_fallbacks_total = c(
            "crypto", "device_fallbacks_total",
            "Device-path batches re-verified on host.", ["reason"])
        self.precomputed_hits_total = c(
            "crypto", "precomputed_hits_total",
            "Batches served entirely from precomputed verdicts.", ["plane"])
        self.pad_waste_ratio = g(
            "crypto", "pad_waste_ratio",
            "Padded-slot fraction of the last device batch.", ["plane"])
        self.vote_queue_depth = g(
            "crypto", "vote_queue_depth",
            "Votes pending in the micro-batcher at last flush.")
        self.vote_flush_latency_seconds = h(
            "crypto", "vote_flush_latency_seconds",
            "Vote micro-batch flush latency.", ["route"],
            buckets=self.LATENCY_BUCKETS)
        # -- device circuit breaker (crypto/breaker.py) ------------------
        self.breaker_state = g(
            "crypto", "breaker_state",
            "Circuit breaker state: 0 closed, 1 open, 2 half-open.",
            ["breaker"])
        self.breaker_transitions_total = c(
            "crypto", "breaker_transitions_total",
            "Circuit breaker state transitions.",
            ["breaker", "from", "to"])


class DeviceMetrics:
    """The device dispatch pipeline (crypto/phases.py recorder): per-segment
    pack / dispatch / fetch phase latencies, per-device dispatch traffic,
    and the pipeline-overlap ratio — the self-measuring successor to the
    hand-built PROFILE_r05.json relay cost model. Offload engines are
    designed from exactly this stage-occupancy breakdown (arXiv 2112.02229)
    and committee-consensus throughput studies attribute wins through it
    (arXiv 2302.00418)."""

    #: phase times span ~100 us (CPU pack of a small chunk) to multi-second
    #: relay fetches
    PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.segment_phase_seconds = h(
            "crypto", "segment_phase_seconds",
            "Seconds per dispatch phase of each device segment "
            "(pack: host wire packing; dispatch: async kernel call; "
            "fetch: dispatch return to verdicts host-resident).",
            ["phase", "plane"], buckets=self.PHASE_BUCKETS)
        self.segment_sigs = h(
            "crypto", "segment_sigs",
            "Signatures per dispatched device segment.", ["plane"],
            buckets=CryptoMetrics.BATCH_BUCKETS)
        self.pipeline_overlap_ratio = g(
            "crypto", "pipeline_overlap_ratio",
            "Last segmented call's in-flight wall over summed in-flight "
            "time (1.0 = serial dispatches, 0.5 = 2-deep fully overlapped).")
        self.device_dispatch_total = c(
            "crypto", "device_dispatch_total",
            "Segments dispatched per device ('host' = batches the scalar "
            "route kept off the device entirely).", ["device"])
        self.device_inflight = g(
            "crypto", "device_inflight",
            "Segments currently in flight per device.", ["device"])
        # -- aggregate-signature (BLS) plane telemetry --------------------
        # PR 17 made commits collapse to one pairing; these series make
        # that pairing visible: wall cost per call, calls per verify mode
        # (full / light / trusting — the three verify_commit* entries),
        # and the wire size the aggregation bought.
        self.pairing_seconds = h(
            "crypto", "pairing_seconds",
            "Wall seconds per aggregate-signature verify call (pack + "
            "subgroup checks + the one pairing), by crypto plane.",
            ["plane"], buckets=self.PHASE_BUCKETS)
        self.aggregate_verify_total = c(
            "crypto", "aggregate_verify_total",
            "Aggregate-signature verifications by scheme and verify mode "
            "(full/light/trusting).", ["scheme", "mode"])
        self.aggregated_commit_bytes = h(
            "crypto", "aggregated_commit_bytes",
            "Encoded wire size of verified aggregated commits (48-byte "
            "agg sig + signer bitmap + overhead; an ed25519 commit at the "
            "same validator count is ~100 B/signer).",
            buckets=(64, 96, 128, 192, 256, 384, 512, 1024, 4096, 16384))


class ProcessMetrics:
    """Process resource watermarks (libs/watermark.py sampler): the
    slow-leak surface. Sampled right before each /metrics render, so
    FleetScraper sees fresh values and the soak plane's leak-slope SLOs
    (bounded RSS/WAL/ring growth, bounded series cardinality) have a
    stream to judge."""

    def __init__(self, reg: Registry):
        g = reg.gauge
        self.rss_bytes = g(
            "process", "rss_bytes",
            "Resident set size of this process in bytes.")
        self.open_fds = g(
            "process", "open_fds",
            "Open file descriptors held by this process.")
        self.wal_bytes = g(
            "process", "wal_bytes",
            "On-disk bytes of this node's WALs including rotated "
            "segments.")
        self.txlife_ring_depth = g(
            "process", "txlife_ring_depth",
            "Sealed tx-lifecycle records currently held in the bounded "
            "ring.")
        self.metric_series = g(
            "process", "metric_series",
            "Rendered series cardinality of this node's own metric "
            "registry (label-set blowups show up here first).")


class FaultMetrics:
    """The fault-injection plane (libs/faults.py): how many injected
    faults actually fired, per site — the denominator every chaos
    assertion divides by."""

    def __init__(self, reg: Registry):
        self.faults_injected_total = reg.counter(
            "faults", "injected_total",
            "Injected faults fired, per site.", ["site"])


class RecoveryMetrics:
    """The crash-recovery plane (wired at node startup): what this boot
    had to repair and how long coming back took — recovery time as a
    measurable, gateable quantity instead of an anecdote. restarts_total
    is fed by the restart supervisor (the e2e runner exports the count/
    reason into the relaunched node's env so the series survives on the
    node's own /metrics)."""

    def __init__(self, reg: Registry):
        g, c = reg.gauge, reg.counter
        self.restarts_total = c(
            "recovery", "restarts_total",
            "Supervised restarts that led to boots of this node, by exit "
            "reason (crash, signal-<n>).", ["reason"])
        self.wal_repairs_total = c(
            "recovery", "wal_repairs_total",
            "Consensus-WAL torn tails truncated by repair-on-open.")
        self.wal_repaired_bytes_total = c(
            "recovery", "wal_repaired_bytes_total",
            "Undecodable bytes removed from the WAL tail at open.")
        self.wal_records_replayed = g(
            "recovery", "wal_records_replayed",
            "WAL records replayed into the state machine at the last boot "
            "(catchup replay for the in-flight height).")
        # attribute keeps the catalog name; the series is
        # tendermint_recovery_duration_seconds (subsystem supplies the
        # prefix — same convention as consensus_stalled_total)
        self.recovery_duration_seconds = g(
            "recovery", "duration_seconds",
            "Seconds from node assembly to consensus ready at the last "
            "boot (stores + handshake + WAL replay + reactor start).")


class BlocksyncMetrics:
    """The fast-sync apply plane (blockchain/reactor.py 2-deep pipeline)."""

    STAGE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.stage_seconds = h(
            "blocksync", "stage_seconds",
            "Seconds per pipeline stage observation "
            "(hash/verify per window, exec/store per block).", ["stage"],
            buckets=self.STAGE_BUCKETS)
        self.window_blocks = h(
            "blocksync", "window_blocks",
            "Blocks applied per verify window.",
            buckets=(1, 2, 4, 8, 16, 32))
        self.pipelined_windows_total = c(
            "blocksync", "pipelined_windows_total",
            "Windows whose stage A overlapped the previous apply.")
        self.inline_windows_total = c(
            "blocksync", "inline_windows_total",
            "Windows verified inline (pipeline starved or first window).")
        self.lookahead_stalls_total = c(
            "blocksync", "lookahead_stalls_total",
            "Iterations where the next window's blocks were not yet "
            "downloaded when the lookahead wanted to start.")
        self.stale_window_discards_total = c(
            "blocksync", "stale_window_discards_total",
            "Prepared windows discarded because the pool or validator set "
            "moved underneath them.")
        # -- adversarial resilience (libs/peerscore.py scoreboard) --------
        self.peer_bans_total = c(
            "blocksync", "peer_bans_total",
            "Block-sync peers banned after repeated bad blocks/commits.",
            ["reason"])
        self.sync_retries_total = c(
            "blocksync", "sync_retries_total",
            "Block windows redone after a bad block from a peer.")


class StateSyncMetrics:
    """The snapshot-restore plane (statesync/ — reference
    statesync/metrics.go, grown the adversarial counters a Byzantine
    bootstrap needs: who lied, how often we retried, and whether the
    victim banned anyone)."""

    RESTORE_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                       120.0, 300.0)

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.snapshots_offered_total = c(
            "statesync", "snapshots_offered_total",
            "Snapshots discovered from peers and added to the pool.")
        self.snapshots_rejected_total = c(
            "statesync", "snapshots_rejected_total",
            "Snapshots rejected during restore.", ["reason"])
        self.chunks_fetched_total = c(
            "statesync", "chunks_fetched_total",
            "Snapshot chunks received and queued.")
        self.chunks_discarded_total = c(
            "statesync", "chunks_discarded_total",
            "Chunks discarded (timeout, app retry, rejected sender).")
        self.chunks_refetched_total = c(
            "statesync", "chunks_refetched_total",
            "Chunks the app explicitly asked to refetch.")
        self.restore_duration_seconds = h(
            "statesync", "restore_duration_seconds",
            "Wall seconds per snapshot restore attempt.",
            ["result"], buckets=self.RESTORE_BUCKETS)
        self.discovery_rounds_total = c(
            "statesync", "discovery_rounds_total",
            "Snapshot re-discovery rounds (pool empty, peers re-asked).")
        self.peer_bans_total = c(
            "statesync", "peer_bans_total",
            "Sync peers banned for serving bad snapshot data.", ["reason"])
        self.sync_retries_total = c(
            "statesync", "sync_retries_total",
            "Chunk fetches retried against another peer.")
        self.fallbacks_total = c(
            "statesync", "fallbacks_total",
            "State-sync attempts abandoned for the fast-sync-from-genesis "
            "fallback (no viable snapshots / providers exhausted).")


class NodeMetrics:
    """All module metric sets over one registry (node/node.go:117
    MetricsProvider)."""

    def __init__(self, namespace: str = "tendermint"):
        self.registry = Registry(namespace)
        self.consensus = ConsensusMetrics(self.registry)
        self.mempool = MempoolMetrics(self.registry)
        self.rpc = RPCMetrics(self.registry)
        self.lightserve = LightServeMetrics(self.registry)
        self.p2p = P2PMetrics(self.registry)
        self.state = StateMetrics(self.registry)
        self.crypto = CryptoMetrics(self.registry)
        self.device = DeviceMetrics(self.registry)
        self.blocksync = BlocksyncMetrics(self.registry)
        self.statesync = StateSyncMetrics(self.registry)
        self.faults = FaultMetrics(self.registry)
        self.recovery = RecoveryMetrics(self.registry)
        self.process = ProcessMetrics(self.registry)
        # tracer ring saturation (libs/trace.py): a bounded ring that
        # silently ate its front reads as "nothing happened early on" —
        # this series (plus the export header's `dropped`) says otherwise
        self.trace_dropped_events_total = self.registry.counter(
            "trace", "dropped_events_total",
            "Trace events pushed off the bounded ring by newer events.")
