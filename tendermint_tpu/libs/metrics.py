"""Prometheus-style metrics, dependency-free
(reference per-module metrics.go + prometheus/client_golang).

Counter / Gauge / Histogram with labels, collected in a Registry that
renders the text exposition format served on the node's
``instrumentation.prometheus_listen_addr`` /metrics endpoint
(reference node/node.go:962).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str) -> "_Bound":
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected {len(self.label_names)} "
                             f"labels, got {len(values)}")
        return _Bound(self, tuple(str(v) for v in values))

    def _fmt_labels(self, lv: Tuple[str, ...]) -> str:
        if not lv:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in zip(self.label_names, lv))
        return "{" + inner + "}"

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._values.items())
        for lv, val in items:
            out.append(f"{self.name}{self._fmt_labels(lv)} {_fmt(val)}")
        return out


class _Bound:
    __slots__ = ("metric", "lv")

    def __init__(self, metric: "_Metric", lv: Tuple[str, ...]):
        self.metric = metric
        self.lv = lv

    def inc(self, amount: float = 1.0) -> None:
        self.metric._inc(self.lv, amount)

    def set(self, value: float) -> None:
        self.metric._set(self.lv, value)

    def observe(self, value: float) -> None:
        self.metric._observe(self.lv, value)


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _inc(self, lv: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[lv] = self._values.get(lv, 0.0) + amount

    def _set(self, lv, value):  # pragma: no cover - misuse guard
        raise TypeError("counters only go up")


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        self._set((), value)

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _set(self, lv: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._values[lv] = float(value)

    def _inc(self, lv: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[lv] = self._values.get(lv, 0.0) + amount


class Histogram(_Metric):
    kind = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float) -> None:
        self._observe((), value)

    def _observe(self, lv: Tuple[str, ...], value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(lv, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[lv] = self._sums.get(lv, 0.0) + value
            self._totals[lv] = self._totals.get(lv, 0) + 1

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            items = sorted(self._counts.items())
            for lv, counts in items:
                for b, c in zip(self.buckets, counts):
                    labels = dict(zip(self.label_names, lv))
                    labels["le"] = _fmt(b)
                    inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
                    out.append(f"{self.name}_bucket{{{inner}}} {c}")
                inf_labels = dict(zip(self.label_names, lv))
                inf_labels["le"] = "+Inf"
                inner = ",".join(f'{k}="{v}"' for k, v in inf_labels.items())
                out.append(f"{self.name}_bucket{{{inner}}} {self._totals[lv]}")
                out.append(f"{self.name}_sum{self._fmt_labels(lv)} "
                           f"{_fmt(self._sums[lv])}")
                out.append(f"{self.name}_count{self._fmt_labels(lv)} "
                           f"{self._totals[lv]}")
        return out


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._add(Counter(self._fq(subsystem, name), help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._add(Gauge(self._fq(subsystem, name), help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str,
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._add(Histogram(self._fq(subsystem, name), help_, labels,
                                   buckets))

    def _fq(self, subsystem: str, name: str) -> str:
        parts = [p for p in (self.namespace, subsystem, name) if p]
        return "_".join(parts)

    def _add(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# --- per-module metric sets (reference consensus/metrics.go etc.) -----------

class ConsensusMetrics:
    """(consensus/metrics.go — the load-bearing subset of its 23 series)"""

    def __init__(self, reg: Registry):
        g, c, h = reg.gauge, reg.counter, reg.histogram
        self.height = g("consensus", "height", "Height of the chain.")
        self.rounds = g("consensus", "rounds", "Round of the chain.")
        self.validators = g("consensus", "validators",
                            "Number of validators.")
        self.validators_power = g("consensus", "validators_power",
                                  "Total voting power of validators.")
        self.missing_validators = g("consensus", "missing_validators",
                                    "Validators missing from the last commit.")
        self.byzantine_validators = g("consensus", "byzantine_validators",
                                      "Validators that equivocated.")
        self.num_txs = g("consensus", "num_txs", "Txs in the latest block.")
        self.block_size_bytes = g("consensus", "block_size_bytes",
                                  "Size of the latest block.")
        self.total_txs = c("consensus", "total_txs", "Total committed txs.")
        self.block_interval_seconds = h(
            "consensus", "block_interval_seconds",
            "Time between this and the last block.")
        self.fast_syncing = g("consensus", "fast_syncing",
                              "Whether the node is fast syncing.")
        self.block_parts = c("consensus", "block_parts",
                             "Block parts transmitted per peer.", ["peer_id"])
        self.quorum_prevote_delay = h(
            "consensus", "quorum_prevote_delay",
            "Seconds from proposal timestamp to 2/3 prevotes.")
        self.missing_validators_power = g(
            "consensus", "missing_validators_power",
            "Voting power of validators missing from the last commit.")
        self.byzantine_validators_power = g(
            "consensus", "byzantine_validators_power",
            "Voting power of validators that equivocated.")
        self.validator_power = g(
            "consensus", "validator_power",
            "This node's voting power (0 when not a validator).")
        self.validator_last_signed_height = g(
            "consensus", "validator_last_signed_height",
            "Last height this node's validator signed.")
        self.validator_missed_blocks = c(
            "consensus", "validator_missed_blocks",
            "Blocks this node's validator missed signing.")
        self.committed_height = g(
            "consensus", "committed_height", "Latest committed height.")
        self.state_syncing = g(
            "consensus", "state_syncing",
            "Whether the node is state syncing.")
        self.proposal_receive_count = c(
            "consensus", "proposal_receive_count",
            "Proposals received.", ["status"])
        self.latest_block_height = g(
            "consensus", "latest_block_height",
            "Alias of committed height for dashboards.")


class MempoolMetrics:
    """(mempool/metrics.go)"""

    def __init__(self, reg: Registry):
        self.size = reg.gauge("mempool", "size", "Number of uncommitted txs.")
        self.tx_size_bytes = reg.histogram(
            "mempool", "tx_size_bytes", "Tx sizes in bytes.",
            buckets=(32, 128, 512, 2048, 8192, 32768, 131072))
        self.failed_txs = reg.counter("mempool", "failed_txs",
                                      "Txs that failed CheckTx.")
        self.recheck_times = reg.counter("mempool", "recheck_times",
                                         "Times txs were rechecked.")


class P2PMetrics:
    """(p2p/metrics.go)"""

    def __init__(self, reg: Registry):
        self.peers = reg.gauge("p2p", "peers", "Connected peers.")
        self.peer_receive_bytes_total = reg.counter(
            "p2p", "peer_receive_bytes_total",
            "Bytes received per channel.", ["chID"])
        self.peer_send_bytes_total = reg.counter(
            "p2p", "peer_send_bytes_total",
            "Bytes sent per channel.", ["chID"])


class StateMetrics:
    """(state/metrics.go)"""

    def __init__(self, reg: Registry):
        self.block_processing_time = reg.histogram(
            "state", "block_processing_time",
            "Seconds in ApplyBlock.", buckets=(0.001, 0.005, 0.01, 0.025,
                                               0.05, 0.1, 0.25, 0.5, 1.0))


class NodeMetrics:
    """All module metric sets over one registry (node/node.go:117
    MetricsProvider)."""

    def __init__(self, namespace: str = "tendermint"):
        self.registry = Registry(namespace)
        self.consensus = ConsensusMetrics(self.registry)
        self.mempool = MempoolMetrics(self.registry)
        self.p2p = P2PMetrics(self.registry)
        self.state = StateMetrics(self.registry)
