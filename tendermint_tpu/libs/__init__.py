"""Host-side utility libraries (the reference's libs/ tier, SURVEY.md §2.15)."""
