"""Signal-triggered in-process diagnostic dump (VERDICT r4 #7).

``cmd debug`` collects its bundle over RPC — useless against a node whose
event loop is wedged, which is precisely when a dump matters. The reference
always carries an out-of-band pprof listener (node/node.go:56,896) and
``debug kill`` snapshots goroutine profiles before the SIGKILL
(cmd/tendermint/commands/debug/kill.go). The analog here: a SIGUSR1 handler
registered with ``signal.signal`` — NOT ``loop.add_signal_handler``, whose
callbacks are loop callbacks and never run while the loop is stuck inside a
callback — that synchronously writes:

* every thread's current stack (``sys._current_frames``);
* every asyncio task of the node's loop with its await stack;
* the consensus round state repr and the open-peer table.

The handler runs between Python bytecodes of whatever the main thread is
executing, so a loop wedged in pure-Python spin still dumps; only a thread
blocked inside a C call with the GIL held can suppress it (same limitation
as Go's SIGQUIT dump for a wedged cgo call).
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import time
import traceback
from typing import Optional

_INSTALLED: dict = {}

# how many trailing trace events land in the dump bundle (full rings are
# 64k events — the tail is what describes the moments before the wedge)
_TRACE_TAIL_EVENTS = 512
# how many trailing device-segment phase records ride in device.json
_DEVICE_SEGMENT_TAIL = 64
# how many sealed heights of the consensus stage timeline ride along
_TIMELINE_TAIL_HEIGHTS = 32
# give the off-thread metrics render this long before the dump moves on
_METRICS_RENDER_TIMEOUT_S = 2.0


def write_dump(out_dir: str, node=None, loop=None, extras=None) -> str:
    """Write stacks + node state under out_dir; returns the dump path.
    ``extras`` is an optional JSON-safe dict the caller wants in the
    bundle (``extras.json``) — e.g. the watchdog's halt classification
    and per-validator vote bitmap."""
    os.makedirs(out_dir, exist_ok=True)

    if extras:
        try:
            import json

            with open(os.path.join(out_dir, "extras.json"), "w") as f:
                json.dump(extras, f, indent=1, default=str)
        except Exception:
            traceback.print_exc(file=sys.stderr)

    with open(os.path.join(out_dir, "threads.txt"), "w") as f:
        for tid, frame in sys._current_frames().items():
            f.write(f"--- thread {tid} ---\n")
            f.write("".join(traceback.format_stack(frame)))
            f.write("\n")

    if loop is not None:
        import asyncio

        with open(os.path.join(out_dir, "tasks.txt"), "w") as f:
            try:
                tasks = asyncio.all_tasks(loop)
            except Exception as e:
                f.write(f"could not enumerate tasks: {e}\n")
                tasks = []
            for task in tasks:
                f.write(f"--- {task!r} ---\n")
                try:
                    for frame in task.get_stack(limit=40):
                        f.write("".join(traceback.format_stack(frame, limit=8)))
                except Exception as e:
                    f.write(f"  <stack unavailable: {e}>\n")
                f.write("\n")

    # metrics-registry snapshot: the same exposition text /metrics serves,
    # but collected without the event loop — works when the RPC/metrics
    # listener's loop is the thing that's wedged. render() takes the metric
    # locks, and this handler may have interrupted the very frame holding
    # one (signal handlers run on the main thread between bytecodes), so it
    # runs on a helper thread with a join timeout instead of deadlocking
    # the node harder than the wedge being diagnosed.
    if node is not None and getattr(node, "metrics", None) is not None:
        try:
            import threading

            path = os.path.join(out_dir, "metrics.prom")

            def _render_and_write():
                try:
                    text = node.metrics.registry.render()
                    with open(path, "w") as f:
                        f.write(text)
                except Exception:
                    traceback.print_exc(file=sys.stderr)

            t = threading.Thread(target=_render_and_write, daemon=True,
                                 name="debugdump-metrics")
            t.start()
            t.join(_METRICS_RENDER_TIMEOUT_S)
            # on timeout the daemon thread finishes the write (or not)
            # once the interrupted frame releases its lock; nothing blocks
        except Exception:
            traceback.print_exc(file=sys.stderr)

    # span-trace ring tail (libs/trace.py): the last hot-path spans before
    # the wedge, loadable in Perfetto like a bench trace
    try:
        import json

        from .trace import tracer

        events = tracer.tail(_TRACE_TAIL_EVENTS)
        if events:
            with open(os.path.join(out_dir, "trace_tail.json"), "w") as f:
                json.dump(tracer.chrome_trace(events), f)
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # per-height stage timeline tail (consensus/timeline.py): a watchdog
    # dump should say WHICH consensus stage the stalled height wedged in —
    # the in-flight record's marks end exactly where progress stopped
    try:
        import json

        tl = getattr(getattr(node, "consensus_state", None), "timeline",
                     None)
        if tl is not None:
            with open(os.path.join(out_dir, "stage_timeline.json"), "w") as f:
                json.dump(tl.snapshot(_TIMELINE_TAIL_HEIGHTS), f, indent=1)
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # device-plane snapshot (crypto/phases.py + libs/compilecache.py): the
    # jax backend + device inventory, cumulative phase stats, the last-N
    # segment records, and whether the persistent compile cache was built
    # for THIS host's CPU features (the cpu_aot_loader SIGILL footgun) —
    # a wedged or SIGILL-adjacent dispatch must be attributable post-mortem
    try:
        import json

        from ..crypto import phases

        doc = {
            "phase_totals": phases.phase_totals(),
            "recent_segments": phases.recent_segments(_DEVICE_SEGMENT_TAIL),
        }
        try:
            # per-device lane health (multi-device pool): which chips are
            # degraded, and the pool's reshard/error counters
            from ..crypto.breaker import lane_breakers

            doc["lane_breakers"] = {
                label: {"state": b.state, "stats": dict(b.stats)}
                for label, b in lane_breakers().items()}
            md = sys.modules.get(
                "tendermint_tpu.crypto.ed25519_jax.multidevice")
            if md is not None and md._POOL is not None:
                doc["multidevice_pool"] = {
                    "lanes": [l.label for l in md._POOL.lanes],
                    "stats": dict(md._POOL.stats)}
        except Exception as e:
            doc["lane_breakers"] = f"unavailable: {e}"
        try:
            from . import compilecache

            doc["compile_cache"] = compilecache.status()
        except Exception as e:
            doc["compile_cache"] = f"unavailable: {e}"
        # report jax only if this process already imported it: a dump
        # handler must never pay (or wedge on) a cold jax/backend init
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                doc["jax_backend"] = jax.default_backend()
                doc["devices"] = [f"{d.platform}:{d.id}"
                                  for d in jax.devices()]
            except Exception as e:
                doc["jax_error"] = f"{type(e).__name__}: {e}"
        else:
            doc["jax_backend"] = None
        with open(os.path.join(out_dir, "device.json"), "w") as f:
            json.dump(doc, f, indent=1, default=str)
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # per-tx lifecycle tail (libs/txlife.py): the ingestion plane's view of
    # the moments before the wedge — which stage sampled txs stalled in,
    # how deep the active map ran, and the last sealed broadcast→commit
    # records with their stage decompositions
    try:
        import json

        tl = getattr(getattr(node, "mempool", None), "txlife", None)
        if tl is not None:
            with open(os.path.join(out_dir, "txlife.json"), "w") as f:
                json.dump(tl.snapshot(_TIMELINE_TAIL_HEIGHTS), f, indent=1)
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # statesync progress (statesync/syncer.py progress()): a bootstrap that
    # wedged mid-restore must be diagnosable post-mortem — which snapshot,
    # how many chunks landed, and which peers were struck/banned
    try:
        import json

        ss = getattr(node, "statesync_reactor", None)
        if ss is not None:
            syncer = getattr(ss, "syncer", None)
            progress = (syncer.progress() if syncer is not None
                        else getattr(ss, "last_progress", None))
            if progress is not None:
                with open(os.path.join(out_dir, "statesync.json"), "w") as f:
                    json.dump(progress, f, indent=1)
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # fleet-rollup snapshot, when a fleet scraper is running alongside this
    # node (e2e runner / bench config 4 export TMTPU_FLEET_JSON and keep the
    # file fresh): the cluster's view of the moment this node stalled
    try:
        fleet = os.environ.get("TMTPU_FLEET_JSON")
        if fleet and os.path.exists(fleet):
            import shutil

            shutil.copy(fleet, os.path.join(out_dir, "fleet_rollup.json"))
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # soak report, when this dump fires during a game-day run (tools/
    # soak.py exports TMTPU_SOAK_REPORT and rewrites the file per SLO
    # evaluation): the chaos schedule + breach attributions in flight
    try:
        soak = os.environ.get("TMTPU_SOAK_REPORT")
        if soak and os.path.exists(soak):
            import shutil

            shutil.copy(soak, os.path.join(out_dir, "soak_report.json"))
    except Exception:
        traceback.print_exc(file=sys.stderr)

    if node is not None:
        with open(os.path.join(out_dir, "node_state.txt"), "w") as f:
            try:
                rs = node.consensus_state.rs
                f.write(f"round_state: height={rs.height} round={rs.round} "
                        f"step={rs.step}\n")
            except Exception as e:
                f.write(f"round_state unavailable: {e}\n")
            try:
                peers = node.switch.peers
                f.write(f"peers ({len(peers)}):\n")
                for pid, peer in list(peers.items()):
                    f.write(f"  {pid} {getattr(peer, 'node_info', None)!r}\n")
            except Exception as e:
                f.write(f"peer table unavailable: {e}\n")
            try:
                f.write(f"blocks_synced: "
                        f"{node.blockchain_reactor.blocks_synced}\n")
            except Exception:
                pass
    return out_dir


def install(home_dir: str, node=None, loop=None,
            signum: int = signal.SIGUSR1) -> None:
    """Register the dump handler; main thread only (CPython rule). Also arms
    faulthandler on SIGABRT so hard crashes leave stacks too."""

    def _handler(_sig, _frame):
        out = os.path.join(home_dir, f"debug-{int(time.time())}")
        try:
            write_dump(out, node=node, loop=loop)
        except Exception:
            traceback.print_exc(file=sys.stderr)

    signal.signal(signum, _handler)
    _INSTALLED[signum] = home_dir
    try:
        faulthandler.enable()
    except Exception:
        pass


def installed_home(signum: int = signal.SIGUSR1) -> Optional[str]:
    return _INSTALLED.get(signum)
