"""Process resource watermarks: RSS, open fds, WAL bytes, txlife ring
depth, metric-series cardinality — the slow-leak surface.

Every other plane measures *throughput*; nothing measured *growth*. A
WAL that never prunes, a sealed-ring that stops evicting, or a metric
registry whose label sets multiply are invisible to invariant checks and
to p99 latency until the box falls over. The sampler reads each
watermark on demand (cheap: one /proc read each) and mirrors it into the
``process_*`` gauges on :class:`~.metrics.ProcessMetrics`, so they ride
the existing /metrics → FleetScraper → soak-SLO pipeline; the leak-slope
objectives in libs/slo.py are evaluated over exactly these series.

Pure helpers are module-level so tools can use them without a node.
"""

from __future__ import annotations

import os
from typing import Iterable

try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):
    _PAGE = 4096


def rss_bytes() -> int:
    """Resident set size. /proc when available, getrusage fallback
    (ru_maxrss is the high-water mark, close enough for slope)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except Exception:
        try:
            import resource
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return 0


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except Exception:
        return 0


def wal_bytes(paths: Iterable) -> int:
    """Total on-disk bytes of the given WAL files including rotated
    segments (``<path>.N`` — see consensus/wal.py rotation). Entries may
    be callables returning a path, for WALs that open after wiring."""
    total = 0
    for path in paths:
        if callable(path):
            try:
                path = path()
            except Exception:
                continue
        if not path:
            continue
        try:
            if os.path.exists(path):
                total += os.path.getsize(path)
            idx = 0
            while os.path.exists(f"{path}.{idx}"):
                total += os.path.getsize(f"{path}.{idx}")
                idx += 1
        except OSError:
            continue
    return total


def registry_series(registry) -> int:
    """Rendered-series cardinality of a metrics Registry: one per live
    label set for counters/gauges; histograms cost bucket+2 lines plus
    the +Inf bucket per label set. Reaches into the registry's internals
    on purpose — rendering the whole exposition to count lines would
    cost more than every other watermark combined."""
    n = 0
    try:
        for m in list(getattr(registry, "_metrics", ())):
            if hasattr(m, "_counts"):    # histogram
                n += len(m._totals) * (len(getattr(m, "buckets", ())) + 3)
            else:
                n += len(getattr(m, "_values", ()))
    except Exception:
        pass
    return n


class ResourceWatermarks:
    """Per-node sampler bound to a ProcessMetrics gauge set.

    ``sample()`` reads every watermark and mirrors it into the gauges;
    the node's /metrics handler calls it right before rendering so every
    scrape carries fresh values without a background task."""

    def __init__(self, metrics=None, txlife=None,
                 wal_paths: Iterable = (),
                 registry=None):
        self.metrics = metrics
        self.txlife = txlife
        self.wal_paths = list(wal_paths)
        self.registry = registry

    def ring_depth(self) -> int:
        tl = self.txlife
        if tl is None:
            return 0
        try:
            return len(tl._ring)
        except Exception:
            return 0

    def sample(self) -> dict:
        vals = {
            "rss_bytes": float(rss_bytes()),
            "open_fds": float(open_fds()),
            "wal_bytes": float(wal_bytes(self.wal_paths)),
            "ring_depth": float(self.ring_depth()),
            "metric_series": float(registry_series(self.registry)),
        }
        m = self.metrics
        if m is not None:
            try:
                m.rss_bytes.set(vals["rss_bytes"])
                m.open_fds.set(vals["open_fds"])
                m.wal_bytes.set(vals["wal_bytes"])
                m.txlife_ring_depth.set(vals["ring_depth"])
                m.metric_series.set(vals["metric_series"])
            except Exception:
                pass
        return vals
