"""Minimal protobuf (proto3) wire-format writer/reader.

The reference encodes every signed/hashed artifact with gogoproto-generated
marshalers (e.g. proto/tendermint/types/canonical.pb.go). We need the exact
bytes — sign-bytes and merkle leaves must match the reference — but not a
general protobuf stack, so this is a deliberate, small, hand-rolled codec:

* proto3 zero-value omission for scalars/bytes/strings;
* non-nullable embedded messages are ALWAYS emitted (gogoproto
  `(gogoproto.nullable) = false` semantics — see BlockID.MarshalToSizedBuffer
  in proto/tendermint/types/types.pb.go:1233-1256, which writes the
  PartSetHeader field unconditionally);
* fields emitted in ascending field-number order (gogo writes back-to-front,
  producing ascending order on the wire);
* google.protobuf.Timestamp via (seconds, nanos) with proto3 omission inside.

Reading support is the mirror image, used for storage/wire decoding.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple, Union

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


def encode_varint(v: int) -> bytes:
    if v < 0:
        v &= (1 << 64) - 1  # negative int64 → 10-byte varint, like protobuf
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def encode_zigzag(v: int) -> bytes:
    return encode_varint((v << 1) ^ (v >> 63))


def tag(field_num: int, wire_type: int) -> bytes:
    return encode_varint((field_num << 3) | wire_type)


class Writer:
    """Append-only field writer. Call methods in ascending field order."""

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    # -- scalars (proto3: zero omitted) --
    def varint(self, field: int, v: int) -> None:
        if v != 0:
            self._buf += tag(field, WIRE_VARINT) + encode_varint(v)

    def bool(self, field: int, v: bool) -> None:
        if v:
            self._buf += tag(field, WIRE_VARINT) + b"\x01"

    def sfixed64(self, field: int, v: int) -> None:
        if v != 0:
            self._buf += tag(field, WIRE_FIXED64) + (v & ((1 << 64) - 1)).to_bytes(8, "little")

    def fixed64(self, field: int, v: int) -> None:
        if v != 0:
            self._buf += tag(field, WIRE_FIXED64) + v.to_bytes(8, "little")

    def bytes(self, field: int, v: bytes) -> None:
        if v:
            self._buf += tag(field, WIRE_BYTES) + encode_varint(len(v)) + v

    def string(self, field: int, v: str) -> None:
        self.bytes(field, v.encode("utf-8"))

    # -- embedded messages --
    def message(self, field: int, body: bytes) -> None:
        """Always emitted (gogoproto nullable=false semantics)."""
        self._buf += tag(field, WIRE_BYTES) + encode_varint(len(body)) + body

    def message_opt(self, field: int, body: "Union[bytes, None]") -> None:
        """Omitted when None (nullable pointer field)."""
        if body is not None:
            self.message(field, body)

    def finish(self) -> bytes:
        return bytes(self._buf)


def timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp body from integer unix-nanoseconds.

    Matches gogo's StdTimeMarshal: seconds (field 1, int64 varint), nanos
    (field 2, int32 varint), each omitted when zero. `nanos` is always in
    [0, 1e9) per the Timestamp spec, even for pre-epoch times.
    """
    seconds, nanos = divmod(ns, 1_000_000_000)
    w = Writer()
    w.varint(1, seconds)
    w.varint(2, nanos)
    return w.finish()


def length_delimited(body: bytes) -> bytes:
    """Varint length prefix (libs/protoio MarshalDelimited — sign-bytes framing)."""
    return encode_varint(len(body)) + body


# ---------------------------------------------------------------------------
# Reading

def decode_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def varint_to_int64(v: int) -> int:
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def as_bytes(v) -> bytes:
    """Guard for nested-message fields: a peer can send any wire type for
    any field number, so decoders must reject varints where they expect
    sub-messages with a clean ValueError (fuzz finding)."""
    if not isinstance(v, (bytes, bytearray)):
        raise ValueError(f"expected length-delimited field, got {type(v).__name__}")
    return bytes(v)


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_num, wire_type, value). value: int for varint/fixed, bytes for len-delimited."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = decode_varint(data, pos)
        field_num, wire_type = key >> 3, key & 7
        if wire_type == WIRE_VARINT:
            v, pos = decode_varint(data, pos)
            yield field_num, wire_type, v
        elif wire_type == WIRE_FIXED64:
            if pos + 8 > n:
                raise ValueError("truncated fixed64")
            yield field_num, wire_type, int.from_bytes(data[pos:pos + 8], "little")
            pos += 8
        elif wire_type == WIRE_BYTES:
            ln, pos = decode_varint(data, pos)
            if pos + ln > n:
                raise ValueError("truncated bytes field")
            yield field_num, wire_type, data[pos:pos + ln]
            pos += ln
        elif wire_type == WIRE_FIXED32:
            if pos + 4 > n:
                raise ValueError("truncated fixed32")
            yield field_num, wire_type, int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire_type}")


def fields_dict(data: bytes) -> Dict[int, List[Union[int, bytes]]]:
    out: Dict[int, List[Union[int, bytes]]] = {}
    for fn, _wt, v in iter_fields(data):
        out.setdefault(fn, []).append(v)
    return out


def parse_timestamp(body: bytes) -> int:
    """Timestamp message body → integer unix-nanoseconds."""
    seconds = nanos = 0
    for fn, _wt, v in iter_fields(body):
        if fn == 1:
            seconds = varint_to_int64(v)
        elif fn == 2:
            nanos = varint_to_int64(v)
    return seconds * 1_000_000_000 + nanos


def read_length_delimited(data: bytes, pos: int = 0) -> Tuple[bytes, int]:
    ln, pos = decode_varint(data, pos)
    if pos + ln > len(data):
        raise ValueError("truncated delimited message")
    return data[pos:pos + ln], pos + ln
