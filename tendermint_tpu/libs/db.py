"""Ordered key-value store behind a tm-db-style interface.

The reference depends on the external tm-db module (goleveldb default —
SURVEY.md §2.11). Here: `MemDB` (sorted in-memory, tests) and `SQLiteDB`
(single-file, transactional, ordered BLOB keys) — both support prefix
iteration and atomic write batches, which is all the stores need.
"""

from __future__ import annotations

import bisect
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class DB:
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        """Half-open [start, end), ordered by raw bytes."""
        raise NotImplementedError

    def iterate_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        return self.iterate(prefix, _prefix_end(prefix))

    def write_batch(self, sets: List[Tuple[bytes, bytes]],
                    deletes: Optional[List[bytes]] = None) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _prefix_end(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None


class MemDB(DB):
    def __init__(self) -> None:
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                self._keys.pop(i)

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            lo = bisect.bisect_left(self._keys, start) if start is not None else 0
            hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
            keys = self._keys[lo:hi]
        if reverse:
            keys = list(reversed(keys))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def write_batch(self, sets, deletes=None) -> None:
        with self._lock:
            for k, v in sets:
                self.set(k, v)
            for k in deletes or []:
                self.delete(k)


class SQLiteDB(DB):
    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)")
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value))
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        q = "SELECT k, v FROM kv"
        cond, args = [], []
        if start is not None:
            cond.append("k >= ?")
            args.append(start)
        if end is not None:
            cond.append("k < ?")
            args.append(end)
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def write_batch(self, sets, deletes=None) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                sets)
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k = ?", [(k,) for k in deletes])
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def open_db(name: str, backend: str = "sqlite", directory: Optional[str] = None) -> DB:
    """tm-db NewDB equivalent: backend selected by config (config.db_backend)."""
    if backend in ("mem", "memdb"):
        return MemDB()
    if backend in ("sqlite", "goleveldb"):  # goleveldb alias: config compatibility
        import os

        assert directory is not None, "sqlite backend needs a directory"
        os.makedirs(directory, exist_ok=True)
        return SQLiteDB(os.path.join(directory, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
