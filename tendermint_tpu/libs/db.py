"""Ordered key-value store behind a tm-db-style interface.

The reference depends on the external tm-db module (goleveldb default —
SURVEY.md §2.11). Here: `MemDB` (sorted in-memory, tests) and `SQLiteDB`
(single-file, transactional, ordered BLOB keys) — both support prefix
iteration and atomic write batches, which is all the stores need.
"""

from __future__ import annotations

import bisect
import errno
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from .fail import fail_point
from .faults import faults


def _injected_db_fault(site: str) -> OSError:
    return OSError(errno.EIO, f"injected fault at {site}")


def _torn_write_cut(n_sets: int) -> "int | None":
    """Evaluate the ``db.torn_write`` site against a batch of n_sets
    records: a fired site returns the seeded prefix length to apply before
    failing (the batch-level analog of a byte-level torn write)."""
    return faults.tear_index("db.torn_write", n_sets)


class DB:
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        """Half-open [start, end), ordered by raw bytes."""
        raise NotImplementedError

    def iterate_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        return self.iterate(prefix, _prefix_end(prefix))

    def write_batch(self, sets: List[Tuple[bytes, bytes]],
                    deletes: Optional[List[bytes]] = None) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _prefix_end(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None


class BufferedDB(DB):
    """Read-through write buffer over a base DB.

    set/delete are staged in memory; get/iterate see the overlay merged over
    the base, so code running inside the buffered scope observes its own
    writes (e.g. load_validators following a pointer record written earlier
    in the same window). flush() applies everything as ONE base write_batch —
    the per-window store-write batching the fast-sync apply plane relies on.
    Not a transaction: flush is called on success AND on error (the staged
    writes describe work that already happened in the app)."""

    def __init__(self, base: DB) -> None:
        self.base = base
        self._sets: Dict[bytes, bytes] = {}
        self._dels: set = set()

    def get(self, key: bytes) -> Optional[bytes]:
        v = self._sets.get(key)
        if v is not None:
            return v
        if key in self._dels:
            return None
        return self.base.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._dels.discard(key)
        self._sets[key] = value

    def delete(self, key: bytes) -> None:
        self._sets.pop(key, None)
        self._dels.add(key)

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        # materialized merge: buffered scopes are one verify-window long, so
        # the simple, obviously-correct view beats a streaming merge
        merged = {k: v for k, v in self.base.iterate(start, end)}
        for k in self._dels:
            merged.pop(k, None)
        for k, v in self._sets.items():
            if (start is None or k >= start) and (end is None or k < end):
                merged[k] = v
        for k in sorted(merged, reverse=reverse):
            yield k, merged[k]

    def write_batch(self, sets, deletes=None) -> None:
        for k, v in sets:
            self.set(k, v)
        for k in deletes or []:
            self.delete(k)

    def pending(self) -> int:
        return len(self._sets) + len(self._dels)

    def flush(self) -> None:
        """Apply the staged window as one base write_batch. fsyncgate
        semantics: a failed flush raises WITHOUT clearing the staged
        writes — the records were handled by the app but are NOT durable,
        and silently dropping them here is exactly the
        handled-but-not-durable hole the chaos suite hunts. Callers treat
        the error as fatal (blockchain reactor → on_fatal) or retry the
        flush; injectable at the base DB's ``db.write_batch`` site."""
        from .trace import tracer

        if self._sets or self._dels:
            with tracer.span("window_flush", n_sets=len(self._sets),
                             n_dels=len(self._dels)):
                self.base.write_batch(list(self._sets.items()),
                                      list(self._dels))
        self._sets.clear()
        self._dels.clear()


class MemDB(DB):
    def __init__(self) -> None:
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                self._keys.pop(i)

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            lo = bisect.bisect_left(self._keys, start) if start is not None else 0
            hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
            keys = self._keys[lo:hi]
        if reverse:
            keys = list(reversed(keys))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def write_batch(self, sets, deletes=None) -> None:
        # chaos site shared with SQLiteDB: a fired fault applies NOTHING
        # (all-or-nothing, like the sqlite transaction)
        faults.inject("db.write_batch", _injected_db_fault)
        # torn-write site: MemDB has no transaction, so a torn batch leaves
        # a PARTIAL prefix applied — the retry (BufferedDB keeps the staged
        # window on error) must land the whole window via idempotent upserts
        cut = _torn_write_cut(len(sets))
        if cut is not None:
            with self._lock:
                for k, v in list(sets)[:cut]:
                    self.set(k, v)
            raise _injected_db_fault("db.torn_write")
        with self._lock:
            for k, v in sets:
                self.set(k, v)
            for k in deletes or []:
                self.delete(k)


class SQLiteDB(DB):
    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)")
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value))
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def iterate(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
                reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        q = "SELECT k, v FROM kv"
        cond, args = [], []
        if start is not None:
            cond.append("k >= ?")
            args.append(start)
        if end is not None:
            cond.append("k < ?")
            args.append(end)
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def write_batch(self, sets, deletes=None) -> None:
        # same chaos site as BufferedDB.flush: the injection lands BEFORE
        # the transaction so a fired fault applies nothing (the sqlite
        # transaction itself already guarantees all-or-nothing)
        faults.inject("db.write_batch", _injected_db_fault)
        # torn-write site: a seeded prefix is staged IN the transaction,
        # then the write dies — sqlite rolls the partial work back, so the
        # base stays untouched and the caller's retry lands the whole window
        cut = _torn_write_cut(len(sets))
        with self._lock:
            if cut is not None:
                self._conn.executemany(
                    "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                    list(sets)[:cut])
                self._conn.rollback()
                raise _injected_db_fault("db.torn_write")
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                sets)
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k = ?", [(k,) for k in deletes])
            # mid-window-flush durability boundary (crashmatrix): the whole
            # batch is staged in the open transaction, nothing committed —
            # a kill here must read back as all-or-nothing on reopen
            fail_point("db.mid_window_flush")
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def open_db(name: str, backend: str = "sqlite", directory: Optional[str] = None) -> DB:
    """tm-db NewDB equivalent: backend selected by config (config.db_backend)."""
    if backend in ("mem", "memdb"):
        return MemDB()
    if backend in ("sqlite", "goleveldb"):  # goleveldb alias: config compatibility
        import os

        assert directory is not None, "sqlite backend needs a directory"
        os.makedirs(directory, exist_ok=True)
        return SQLiteDB(os.path.join(directory, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
