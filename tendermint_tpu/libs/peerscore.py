"""Untrusted-peer scoring for the sync planes — ban-after-K with
exponential backoff.

A bootstrapping node talks to peers it has no reason to trust: a snapshot
advertiser can lie about hashes, a chunk server can return garbage, a
block server can tamper a commit, a light-client witness can diverge from
the primary ("Practical Light Clients for Committee-Based Blockchains",
arXiv 2410.03347, assumes exactly this adversary). The p2p trust store
(p2p/trust.py) guards the CONNECTION layer; this scoreboard guards the
SYNC layer — which peer do I ask for the next chunk/block/header — where
the caller wants graded responses, not just connect/refuse:

* a failure puts the peer in exponential backoff (base doubling per
  consecutive failure, seeded jitter so herds of retries don't align);
* ``ban_threshold`` consecutive failures ban it outright;
* a success clears the consecutive count (honest-but-slow peers recover).

Shared by ``statesync/syncer.py`` (chunk fetch + snapshot blame),
``blockchain/reactor.py::_punish`` (bad block/commit providers) and
``light/client.py`` (diverging witnesses). Metrics are injected counters
(``peer_bans_total{reason}``, ``sync_retries_total``) so each plane's
series land on its own subsystem.

Determinism: jitter draws come from one ``random.Random`` seeded by
(seed, name), and ``eligible()`` order is the caller-supplied order (use
sorted peer ids) — a chaos run with a fixed ``TMTPU_FAULTS_SEED`` replays
its ban/backoff schedule exactly.
"""

from __future__ import annotations

import random
import time
import zlib
from typing import Callable, Dict, Iterable, List, Optional


class _PeerScore:
    __slots__ = ("consecutive_failures", "total_failures", "successes",
                 "banned", "ban_reason", "next_eligible_ts")

    def __init__(self):
        self.consecutive_failures = 0
        self.total_failures = 0
        self.successes = 0
        self.banned = False
        self.ban_reason = ""
        self.next_eligible_ts = 0.0


class PeerScoreboard:
    """Per-peer failure bookkeeping with backoff + ban-after-K."""

    def __init__(self, ban_threshold: int = 3, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0, jitter: float = 0.25,
                 seed: int = 0, name: str = "sync",
                 bans_counter=None, retries_counter=None,
                 clock: Callable[[], float] = time.monotonic):
        if ban_threshold < 1:
            raise ValueError("ban_threshold must be >= 1")
        self.ban_threshold = ban_threshold
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.name = name
        self.bans_counter = bans_counter        # Counter with ["reason"]
        self.retries_counter = retries_counter  # plain Counter
        self._clock = clock
        self._rng = random.Random(zlib.crc32(f"{seed}|{name}|score".encode()))
        self._peers: Dict[str, _PeerScore] = {}

    # -- event recording -----------------------------------------------------

    def record_failure(self, peer_id: str, reason: str = "error",
                       severe: bool = False) -> bool:
        """One bad response from `peer_id`; returns True when the peer is
        now (or already was) banned. Backoff doubles per consecutive
        failure, with seeded jitter on top.

        ``severe=True`` is for PROVEN lies — an app-verified corrupted
        chunk, a snapshot failing its trusted hash, a diverging witness —
        and bans immediately: cryptographic evidence doesn't need K
        repetitions, while circumstantial failures (timeouts,
        unavailability) accumulate toward ban_threshold."""
        s = self._peers.setdefault(peer_id, _PeerScore())
        if s.banned:
            return True
        s.consecutive_failures += self.ban_threshold if severe else 1
        s.total_failures += 1
        backoff = min(self.backoff_base_s * 2 ** (s.consecutive_failures - 1),
                      self.backoff_max_s)
        backoff *= 1.0 + self.jitter * self._rng.random()
        s.next_eligible_ts = self._clock() + backoff
        if s.consecutive_failures >= self.ban_threshold:
            s.banned = True
            s.ban_reason = reason
            if self.bans_counter is not None:
                self.bans_counter.labels(reason).inc()
        return s.banned

    def record_success(self, peer_id: str) -> None:
        s = self._peers.setdefault(peer_id, _PeerScore())
        s.successes += 1
        if not s.banned:
            s.consecutive_failures = 0
            s.next_eligible_ts = 0.0

    def note_retry(self) -> None:
        """Count one retried fetch (chunk refetch, block redo, snapshot
        re-discovery round) on the injected sync_retries_total counter."""
        if self.retries_counter is not None:
            self.retries_counter.inc()

    # -- queries -------------------------------------------------------------

    def banned(self, peer_id: str) -> bool:
        s = self._peers.get(peer_id)
        return s is not None and s.banned

    def in_backoff(self, peer_id: str) -> bool:
        s = self._peers.get(peer_id)
        return (s is not None and not s.banned
                and self._clock() < s.next_eligible_ts)

    def eligible(self, peer_ids: Iterable[str],
                 allow_backoff: bool = False) -> List[str]:
        """Filter to peers we may ask right now, preserving caller order.
        ``allow_backoff=True`` re-admits backing-off (not banned) peers —
        the last-resort pool when every eligible peer is exhausted."""
        now = self._clock()
        out = []
        for pid in peer_ids:
            s = self._peers.get(pid)
            if s is None:
                out.append(pid)
                continue
            if s.banned:
                continue
            if not allow_backoff and now < s.next_eligible_ts:
                continue
            out.append(pid)
        return out

    def ban_count(self) -> int:
        return sum(1 for s in self._peers.values() if s.banned)

    # -- maintenance / introspection -----------------------------------------

    def forget(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)

    def reset(self) -> None:
        self._peers.clear()

    def snapshot(self) -> Dict[str, Dict]:
        """JSON-safe per-peer view for debugdump bundles."""
        now = self._clock()
        return {
            pid: {
                "consecutive_failures": s.consecutive_failures,
                "total_failures": s.total_failures,
                "successes": s.successes,
                "banned": s.banned,
                "ban_reason": s.ban_reason,
                "backoff_remaining_s": round(
                    max(0.0, s.next_eligible_ts - now), 3),
            }
            for pid, s in self._peers.items()
        }
