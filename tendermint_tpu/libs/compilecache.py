"""Persistent XLA compile-cache setup with host-feature fingerprinting.

The persistent compile cache is load-bearing (the ed25519 verify kernels
take minutes to compile cold on CPU), but it carries a footgun: XLA:CPU
caches AOT-compiled machine code, and a cache directory populated on a
machine with different CPU features loads anyway — ``cpu_aot_loader``
prints a wall of "Machine type used for XLA:CPU compilation doesn't match
the machine type for execution ... could lead to execution errors such as
SIGILL" to stderr (see MULTICHIP_r05.json's tail for the real artifact) and
the process may die mid-dispatch.

This module is the one place cache dirs get enabled. It stamps each cache
directory with a host fingerprint (machine arch + a hash of the CPU
feature flags) on first use and, when a later process finds a stamp from a
DIFFERENT host, returns a loud human-readable warning for the caller to
log at startup — instead of the risk living only in buried stderr. The
last check's outcome is kept in module state so debugdump's ``device.json``
can carry it post-mortem (:func:`status`).

Fingerprinting is advisory: any I/O failure degrades to "no warning", never
to a broken cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import tempfile
import time
from typing import Dict, Optional

#: stamp file written inside the cache dir (ignored by XLA's key lookups)
MARKER_NAME = "tmtpu_host_fingerprint.json"

_status: Dict = {"cache_dir": None, "fingerprint": None, "marker": None,
                 "mismatch": None}


def _cpu_flags() -> str:
    """Sorted CPU feature flags from /proc/cpuinfo ('' when unavailable —
    e.g. macOS — which degrades to arch-only fingerprinting)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return " ".join(sorted(line.split(":", 1)[1].split()))
    except OSError:
        pass
    return ""


def host_fingerprint() -> Dict:
    flags = _cpu_flags()
    return {
        "machine": platform.machine(),
        "flags_sha256": hashlib.sha256(flags.encode()).hexdigest(),
        "n_flags": len(flags.split()),
    }


def check_cache_dir(cache_dir: str) -> Optional[str]:
    """Stamp ``cache_dir`` with this host's fingerprint, or compare against
    an existing stamp. Returns a warning string when the cache was built on
    a host with different CPU features (the cpu_aot_loader SIGILL risk),
    else None."""
    fp = host_fingerprint()
    _status.update(cache_dir=cache_dir, fingerprint=fp, marker=None,
                   mismatch=None)
    marker = os.path.join(cache_dir, MARKER_NAME)
    try:
        prev = None
        if os.path.exists(marker):
            try:
                with open(marker) as f:
                    prev = json.load(f)
            except (OSError, ValueError):
                prev = None  # torn/unreadable marker: re-stamp below —
                # a broken marker must not silently disable the warning
        if prev is not None:
            _status["marker"] = prev
            if (prev.get("machine"), prev.get("flags_sha256")) != \
                    (fp["machine"], fp["flags_sha256"]):
                warn = (
                    f"persistent XLA compile cache {cache_dir!r} was built "
                    f"on a host with different CPU features (cache: "
                    f"{prev.get('machine')}/"
                    f"{str(prev.get('flags_sha256'))[:12]}, this host: "
                    f"{fp['machine']}/{fp['flags_sha256'][:12]}) — cached "
                    "XLA:CPU AOT kernels can SIGILL at dispatch "
                    "(cpu_aot_loader); delete the cache directory to "
                    "recompile for this host")
                _status["mismatch"] = warn
                return warn
        else:
            os.makedirs(cache_dir, exist_ok=True)
            # a marker-less dir that ALREADY holds cache entries predates
            # the fingerprint (or was copied here): its origin is
            # unverifiable — the MULTICHIP_r05 scenario exactly. Warn once,
            # then stamp with origin recorded, so a cache genuinely built
            # on this host doesn't cry wolf forever while a copied one
            # still got its one loud startup warning.
            has_entries = any(not name.startswith(MARKER_NAME)
                              for name in os.listdir(cache_dir))
            doc = dict(fp, written_unix=time.time(),
                       origin=("preexisting-unverified" if has_entries
                               else "fresh"))
            # unique tmp per process: N nodes pointed at one shared
            # TMTPU_JAX_CACHE all stamp at first start, and a fixed tmp
            # path could interleave writers into a torn marker
            fd, tmp = tempfile.mkstemp(prefix=MARKER_NAME + ".",
                                       dir=cache_dir)
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, marker)
            _status["marker"] = doc
            if has_entries:
                warn = (
                    f"persistent XLA compile cache {cache_dir!r} already "
                    "holds entries but carries no host fingerprint — if it "
                    "was copied from another machine its XLA:CPU AOT "
                    "kernels can SIGILL at dispatch (cpu_aot_loader). "
                    "Stamped with THIS host's fingerprint; delete the "
                    "cache directory if it came from elsewhere")
                _status["mismatch"] = warn
                return warn
    except Exception:
        pass  # advisory only
    return None


def enable_compile_cache(cache_dir: str,
                         min_compile_secs: int = 2) -> Optional[str]:
    """Point jax's persistent compile cache at ``cache_dir`` (config API,
    not env: this image's sitecustomize imports jax at interpreter startup,
    so import-time env reads have already happened) and run the host-
    fingerprint check. Returns the mismatch warning for the caller to log,
    or None."""
    warn = check_cache_dir(cache_dir)
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:
        pass
    return warn


def status() -> Dict:
    """Last check's outcome (for debugdump device.json)."""
    return dict(_status)
