"""Merlin transcripts over STROBE-128 (keccak-f[1600]).

Used by the p2p SecretConnection handshake to bind the STS transcript
(reference p2p/conn/secret_connection.go:92 uses github.com/gtank/merlin).
Implements exactly the subset Merlin needs from STROBE v1.0.2: meta-AD, AD,
PRF (merlin-rust's strobe.rs mini-STROBE), plus the transcript framing
(``dom-sep`` / LE32 length prefixes).

Pure Python; handshake-time only (a few permutations per connection), so
speed is irrelevant. Byte-compatibility with gtank/merlin (and merlin-rust)
is pinned by tests/test_p2p_tcp.py::test_merlin_transcript_matches_upstream_
vector against the canonical merlin transcript test vector.
"""

from __future__ import annotations

# --- keccak-f[1600] ---------------------------------------------------------

_ROUNDS = 24
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rol(x: int, n: int) -> int:
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _MASK


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation of a 200-byte state (little-endian lanes)."""
    a = [[int.from_bytes(state[8 * (x + 5 * y):8 * (x + 5 * y) + 8], "little")
          for y in range(5)] for x in range(5)]
    for rnd in range(_ROUNDS):
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y] & _MASK)
        # iota
        a[0][0] ^= _RC[rnd]
    for x in range(5):
        for y in range(5):
            state[8 * (x + 5 * y):8 * (x + 5 * y) + 8] = a[x][y].to_bytes(8, "little")


# --- mini-STROBE-128 (merlin-rust strobe.rs subset) -------------------------

_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5

_RATE = 166  # 200 - 128/4 - 2


class Strobe128:
    def __init__(self, protocol_label: bytes):
        self._st = bytearray(200)
        self._st[0:6] = bytes([1, _RATE + 2, 1, 0, 1, 96])
        self._st[6:18] = b"STROBEv1.0.2"
        keccak_f1600(self._st)
        self._pos = 0
        self._pos_begin = 0
        self._cur_flags = 0
        self.meta_ad(protocol_label, more=False)

    def _run_f(self) -> None:
        self._st[self._pos] ^= self._pos_begin
        self._st[self._pos + 1] ^= 0x04
        self._st[_RATE + 1] ^= 0x80
        keccak_f1600(self._st)
        self._pos = 0
        self._pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self._st[self._pos] ^= byte
            self._pos += 1
            if self._pos == _RATE:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self._st[self._pos]
            self._st[self._pos] = 0
            self._pos += 1
            if self._pos == _RATE:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int) -> None:
        assert not flags & _FLAG_T, "mini-STROBE has no transport ops"
        old_begin = self._pos_begin
        self._pos_begin = self._pos + 1
        self._cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if flags & (_FLAG_C | _FLAG_K) and self._pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        if not more:
            self._begin_op(_FLAG_M | _FLAG_A)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        if not more:
            self._begin_op(_FLAG_A)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        if not more:
            self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C)
        return self._squeeze(n)


# --- Merlin transcript ------------------------------------------------------

def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class Transcript:
    """merlin::Transcript equivalent (append_message / challenge_bytes)."""

    def __init__(self, label: bytes):
        self._strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self._strobe.meta_ad(label, more=False)
        self._strobe.meta_ad(_le32(len(message)), more=True)
        self._strobe.ad(message, more=False)

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self._strobe.meta_ad(label, more=False)
        self._strobe.meta_ad(_le32(n), more=True)
        return self._strobe.prf(n)
