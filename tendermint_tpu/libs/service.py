"""BaseService lifecycle (reference libs/service/service.go:24,97).

The reference threads every long-lived component (reactors, pools, servers)
through BaseService: Start/Stop are idempotent-with-error, OnStart/OnStop
are the only overridable hooks, Quit exposes completion, Reset re-arms a
stopped service. Components here historically hand-rolled `_started` flags;
this is the shared abstraction, asyncio-flavored: ``wait()`` awaits the quit
event instead of receiving on a channel.

Adoption note: existing components keep their ad-hoc guards (each is tested
through restart paths); new components should subclass this instead.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

logger = logging.getLogger("tmtpu.service")


class ServiceError(Exception):
    pass


class AlreadyStarted(ServiceError):
    """(service.go ErrAlreadyStarted)"""


class AlreadyStopped(ServiceError):
    """(service.go ErrAlreadyStopped)"""


class NotStarted(ServiceError):
    """(service.go ErrNotStarted)"""


class BaseService:
    def __init__(self, name: str):
        self.name = name
        self._started = False
        self._stopped = False
        self._quit: Optional[asyncio.Event] = None

    # -- lifecycle (service.go:139 Start, :171 Stop, :192 Reset) -----------

    async def start(self) -> None:
        if self._stopped:  # checked first: a stopped service stays "started"
            raise AlreadyStopped(f"{self.name}: stopped, call reset() first")
        if self._started:
            raise AlreadyStarted(self.name)
        self._started = True
        self._quit = asyncio.Event()
        logger.debug("starting %s", self.name)
        try:
            await self.on_start()
        except Exception:
            self._started = False
            raise

    async def stop(self) -> None:
        if self._stopped:
            raise AlreadyStopped(self.name)
        if not self._started:
            raise NotStarted(self.name)
        self._stopped = True
        logger.debug("stopping %s", self.name)
        try:
            await self.on_stop()
        finally:
            if self._quit is not None:
                self._quit.set()

    async def reset(self) -> None:
        """Re-arm a STOPPED service (service.go:192: reset of a running
        service is an error)."""
        if not self._stopped:
            raise ServiceError(f"{self.name}: can't reset a running service")
        self._started = False
        self._stopped = False
        self._quit = None

    def is_running(self) -> bool:
        return self._started and not self._stopped

    async def wait(self) -> None:
        """Block until the service stops (service.go Quit channel)."""
        if self._quit is None:
            raise NotStarted(self.name)
        await self._quit.wait()

    # -- hooks -------------------------------------------------------------

    async def on_start(self) -> None:  # pragma: no cover - default no-op
        pass

    async def on_stop(self) -> None:  # pragma: no cover - default no-op
        pass

    def __str__(self) -> str:
        state = ("running" if self.is_running()
                 else "stopped" if self._stopped else "new")
        return f"{self.name}({state})"
