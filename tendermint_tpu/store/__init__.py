"""Block storage (reference store/store.go)."""

from .block_store import BlockStore, BlockStoreState  # noqa: F401
