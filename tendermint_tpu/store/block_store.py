"""BlockStore: block metas/parts/commits by height (reference store/store.go:33).

Key layout mirrors the reference (store/store.go:434-456): H:<h> meta,
P:<h>:<i> part, C:<h> last commit, SC:<h> seen commit, BH:<hash> → height,
plus the blockStore state record holding (base, height) for pruning.
"""

from __future__ import annotations

import json
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..libs.db import DB, BufferedDB
from ..types.basic import BlockID
from ..types.block import Block, BlockMeta, Commit
from ..types.part_set import Part, PartSet


def _meta_key(h: int) -> bytes:
    return f"H:{h}".encode()


def _part_key(h: int, i: int) -> bytes:
    return f"P:{h}:{i}".encode()


def _commit_key(h: int) -> bytes:
    return f"C:{h}".encode()


def _seen_commit_key(h: int) -> bytes:
    return f"SC:{h}".encode()


def _hash_key(hash_: bytes) -> bytes:
    return b"BH:" + hash_.hex().encode()


_STORE_KEY = b"blockStore"


@dataclass
class BlockStoreState:
    base: int = 0
    height: int = 0


class BlockStore:
    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        st = self._load_state()
        self._base = st.base
        self._height = st.height

    # -- state record ------------------------------------------------------

    def _load_state(self) -> BlockStoreState:
        raw = self._db.get(_STORE_KEY)
        if raw is None:
            return BlockStoreState()
        d = json.loads(raw.decode())
        return BlockStoreState(d.get("base", 0), d.get("height", 0))

    def _save_state(self) -> None:
        self._db.set(_STORE_KEY, json.dumps(
            {"base": self._base, "height": self._height}).encode())

    # -- accessors ---------------------------------------------------------

    def base(self) -> int:
        with self._mtx:
            return self._base

    def height(self) -> int:
        with self._mtx:
            return self._height

    def size(self) -> int:
        with self._mtx:
            return self._height - self._base + 1 if self._height > 0 else 0

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self._db.get(_meta_key(height))
        return BlockMeta.decode(raw) if raw is not None else None

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            raw = self._db.get(_part_key(height, i))
            if raw is None:
                return None
            parts.append(Part.decode(raw).bytes_)
        return Block.decode(b"".join(parts))

    def load_block_by_hash(self, hash_: bytes) -> Optional[Block]:
        raw = self._db.get(_hash_key(hash_))
        if raw is None:
            return None
        return self.load_block(int(raw.decode()))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self._db.get(_part_key(height, index))
        return Part.decode(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The canonical commit for height, stored at height+1 save time."""
        raw = self._db.get(_commit_key(height))
        return Commit.decode(raw) if raw is not None else None

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        raw = self._db.get(_seen_commit_key(height))
        return Commit.decode(raw) if raw is not None else None

    # -- writes ------------------------------------------------------------

    @contextmanager
    def window_batch(self):
        """Stage every write inside the scope and flush them as ONE DB
        write-batch at exit (fast-sync applies a 16-block window per
        iteration; per-block write_batch + state-record writes were a
        measurable share of apply wall-clock). Reads inside the scope see
        the staged writes. Flushes on error too — staged writes describe
        blocks whose ABCI commit already happened. Reentrant: a nested
        scope joins the outer batch."""
        with self._mtx:
            nested = isinstance(self._db, BufferedDB)
            if not nested:
                buf = BufferedDB(self._db)
                self._db = buf
        if nested:  # outside the mutex: the outer scope owns the flush
            yield self
            return
        try:
            yield self
        finally:
            with self._mtx:
                # flush BEFORE unhooking: on a flush fault (injected or
                # real EIO) the staged window stays reachable as self._db,
                # so reads remain consistent with the handled-but-not-yet-
                # durable state while the fatal handler runs
                buf.flush()
                self._db = buf.base

    def save_block(self, block: Block, block_parts: PartSet, seen_commit: Commit) -> None:
        """(store/store.go:332 SaveBlock)"""
        height = block.header.height
        with self._mtx:
            expected = self._height + 1
            if self._height > 0 and height != expected:
                raise ValueError(f"BlockStore can only save contiguous blocks. Wanted {expected}, got {height}")
            block_id = BlockID(block.hash(), block_parts.header())
            # parts ARE the encoding split, so their byte total is the block
            # size — re-encoding the whole block just to measure it doubled
            # the save path's proto work
            meta = BlockMeta(block_id, block_parts.byte_size, block.header,
                             len(block.data.txs))
            sets: List[Tuple[bytes, bytes]] = [
                (_meta_key(height), meta.encode()),
                (_hash_key(block.hash()), str(height).encode()),
            ]
            for i in range(block_parts.total):
                part = block_parts.get_part(i)
                sets.append((_part_key(height, i), part.encode()))
            if block.last_commit is not None:
                sets.append((_commit_key(height - 1), block.last_commit.encode()))
            sets.append((_seen_commit_key(height), seen_commit.encode()))
            self._db.write_batch(sets)
            if self._base == 0:
                self._base = height
            self._height = height
            self._save_state()

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        self._db.set(_seen_commit_key(height), commit.encode())

    def prune_blocks(self, retain_height: int) -> int:
        """Remove blocks below retain_height; returns count pruned
        (store/store.go:248)."""
        with self._mtx:
            if retain_height <= 0 or retain_height > self._height:
                raise ValueError(f"cannot prune to height {retain_height}")
            if retain_height <= self._base:
                return 0
            pruned = 0
            deletes: List[bytes] = []
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                deletes.append(_meta_key(h))
                deletes.append(_hash_key(meta.header.hash() or b""))
                for i in range(meta.block_id.part_set_header.total):
                    deletes.append(_part_key(h, i))
                deletes.append(_commit_key(h))
                deletes.append(_seen_commit_key(h))
                pruned += 1
            # durability boundary (crashmatrix): the prune set is chosen but
            # not applied — a kill here must leave either the pre-prune or
            # post-prune store, never a half-readable base
            from ..libs.fail import fail_point

            fail_point("prune.mid_blocks")
            self._db.write_batch([], deletes)
            self._base = retain_height
            self._save_state()
            return pruned

    def load_base_meta(self) -> Optional[BlockMeta]:
        with self._mtx:
            return self.load_block_meta(self._base) if self._base > 0 else None
