"""Benchmarks: every BASELINE.md config, one JSON line each.

Output contract: each line is {"metric", "value", "unit", "vs_baseline"}.
The FLAGSHIP metric — sustained VerifyCommit throughput at 10,240
validators (the north star scale, reference types/validator_set.go:667) —
prints LAST so the driver records it.

Configs (BASELINE.json):
  1  Ed25519 batched stream, CHUNK-sig chunks scanned in one execution
  2  ValidatorSet.VerifyCommit over a 150-validator commit (one-shot)
  3  VerifyCommitLight+Trusting over a 1000-validator header chain
  4  4-node localnet (kvstore), consensus end-to-end blocks/min
  5  fast-sync windowed replay @ 1000 validators
  10k  sustained VerifyCommit @ 10,240 validators (flagship, last)

Baselines: configs 1/2/3/5/10k measure the host scalar loop (OpenSSL-backed
PubKey.verify_signature — the stand-in for the reference's Go x/crypto
ed25519.Verify hot call, crypto/ed25519/ed25519.go:148-155) in the same
process. Config 4's baseline is the reference QA testnet's 19.5 blocks/min
(docs/qa/v034/README.md:141-142; 200-node WAN vs 4-node localhost — an
anchor, not an equal-hardware comparison).

The device path is charged end-to-end: host packing + transfer + kernel +
verdict fetch, exactly what the consensus/blocksync callers pay.
"""

import argparse
import json
import os
import time

import numpy as np

N_STREAM = 32768
CHUNK = 2048
N_BASE = 2048


def _enable_compile_cache():
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(__file__), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass


def _emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(value, 3), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def build_batch(n: int):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    rng = np.random.default_rng(7)
    pks, msgs, sigs, pubs = [], [], [], []
    for i in range(n):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub_bytes = priv.public_key().public_bytes_raw()
        # realistic vote sign-bytes (unique timestamp per validator)
        msg = vote_sign_bytes("bench-chain", SignedMsgType.PRECOMMIT, 100, 0,
                              bid, 1_700_000_000_000_000_000 + i)
        pks.append(pub_bytes)
        msgs.append(msg)
        sigs.append(priv.sign(msg))
        pubs.append(crypto.Ed25519PubKey(pub_bytes))
    return pks, msgs, sigs, pubs


def _host_rate(pubs, msgs, sigs, n: int) -> float:
    """Host scalar loop sigs/s on an n-item subset."""
    t0 = time.perf_counter()
    ok = all(pub.verify_signature(m, s)
             for pub, m, s in zip(pubs[:n], msgs[:n], sigs[:n]))
    elapsed = time.perf_counter() - t0
    assert ok
    return n / elapsed


def bench_stream():
    """Config #1: sustained batched-verifier throughput on vote sign-bytes."""
    pks, msgs, sigs, pubs = build_batch(N_STREAM)

    from tendermint_tpu.crypto.ed25519_jax import batch_verify_stream

    out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)  # compile
    assert np.asarray(out).all(), "warmup stream rejected valid sigs"
    times = []
    for _ in range(4):
        t0 = time.perf_counter()
        out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        times.append(time.perf_counter() - t0)
    assert np.asarray(out).all()
    dev = N_STREAM / min(times)
    host = _host_rate(pubs, msgs, sigs, N_BASE)
    _emit(f"verify_commit_sigs_per_sec_stream{CHUNK}", dev, "sigs/s",
          dev / host, chunk=CHUNK)


# --- commit helpers ---------------------------------------------------------

def _mk_val_set(n_vals: int, seed: int = 7):
    """A validator set + its signing keys (OpenSSL), reusable across heights."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet

    rng = np.random.default_rng(seed)
    keys = {}
    vals = []
    for _ in range(n_vals):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        keys[pub.address()] = sk
        vals.append(Validator(pub.address(), pub, 10))
    return ValidatorSet(vals), keys


def _sign_commit(vs, keys, height: int, chain_id: str):
    """A canonical commit for `height` signed by every validator, in
    validator-set order."""
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(hash(("bench", height)).to_bytes(8, "big", signed=True) * 4,
                  PartSetHeader(1, b"\x02" * 32))
    sigs = []
    for i, v in enumerate(vs.validators):
        ts = 1_700_000_000_000_000_000 + height * 1_000_000 + i
        msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, height, 0,
                              bid, ts)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts,
                              keys[v.address].sign(msg)))
    return Commit(height, 0, bid, sigs), bid


def _timed(fn, warm: int = 1, runs: int = 3) -> float:
    for _ in range(warm):
        fn()
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_verify_commit_150():
    """Config #2: ValidatorSet.VerifyCommit over a 150-validator commit
    (reference types/validator_set.go:667). One-shot: a single interactive
    commit pays the full dispatch latency, so through a remote relay the
    auto backend keeps it on host (break-even ~16 sigs on local silicon)."""
    vs, keys = _mk_val_set(150)
    commit, bid = _sign_commit(vs, keys, 100, "bench-150")
    dev = _timed(lambda: vs.verify_commit("bench-150", bid, 100, commit))
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(lambda: vs.verify_commit("bench-150", bid, 100, commit))
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    _emit("verify_commit_150_vals_sigs_per_sec", 150 / dev, "sigs/s",
          host / dev)


def bench_light_chain_1000():
    """Config #3: light-client VerifyCommitLight+Trusting over a
    1000-validator header chain (reference validator_set.go:722,775,
    light/verifier.go:32). Device path = verify_chain_batched: every
    signature across the range rides ONE device call."""
    from tendermint_tpu.crypto.batch import BatchVerifier, precomputed_verdicts

    n_vals, n_headers = 1000, 8
    vs, keys = _mk_val_set(n_vals)
    commits = [_sign_commit(vs, keys, h, "bench-light")[0]
               for h in range(2, n_headers + 2)]
    trust = (1, 3)

    def verify_chain_device():
        # the chain-batched pattern: batch ALL sigs, then replay semantics
        bv = BatchVerifier(backend="jax")
        pre_keys = []
        for c in commits:
            for idx, cs in enumerate(c.signatures):
                if cs.for_block():
                    pk = vs.validators[idx].pub_key
                    sb = c.vote_sign_bytes("bench-light", idx)
                    bv.add(pk, sb, cs.signature)
                    pre_keys.append((pk.bytes(), sb, cs.signature))
        _, verdicts = bv.verify()
        token = precomputed_verdicts.set(
            {k: bool(v) for k, v in zip(pre_keys, verdicts)})
        try:
            for c in commits:
                vs.verify_commit_light_trusting("bench-light", c, trust)
                vs.verify_commit_light("bench-light", c.block_id, c.height, c)
        finally:
            precomputed_verdicts.reset(token)

    def verify_chain():
        for c in commits:
            vs.verify_commit_light_trusting("bench-light", c, trust)
            vs.verify_commit_light("bench-light", c.block_id, c.height, c)

    dev = _timed(verify_chain_device)
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(verify_chain, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    # sigs verified per pass: trusting tallies ~all, light stops at 2/3
    sigs = n_headers * (n_vals + 2 * n_vals // 3 + 1)
    _emit("light_chain_1000_vals_sigs_per_sec", sigs / dev, "sigs/s",
          host / dev)


def bench_fast_sync_replay():
    """Config #5 (scaled): the block-sync engine's windowed batched commit
    verification over a 1000-validator chain (reference
    blockchain/v0/reactor.go:255; our blockchain/reactor.py). Measures
    the verification plane, which is the reference's fast-sync bottleneck."""
    from tendermint_tpu.types.validator_set import verify_commit_light_batched

    n_vals, n_blocks, window = 1000, 64, 16
    vs, keys = _mk_val_set(n_vals)
    entries = []
    for h in range(1, n_blocks + 1):
        commit, bid = _sign_commit(vs, keys, h, "bench-sync")
        entries.append((vs, "bench-sync", bid, h, commit))

    def replay():
        for i in range(0, n_blocks, window):
            errs = verify_commit_light_batched(entries[i:i + window])
            assert all(e is None for e in errs), errs

    dev = _timed(replay)
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(replay, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    _emit("fast_sync_1000_vals_blocks_per_sec", n_blocks / dev, "blocks/s",
          host / dev)


def bench_localnet():
    """Config #4: 4-node localnet over TCP (kvstore app), consensus reactor
    end-to-end. Measures blocks/min across the net and broadcast_tx_commit
    latency. Baseline anchor: reference 200-node QA testnet 19.5 blocks/min
    (docs/qa/v034/README.md:141-142)."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.request

    root = tempfile.mkdtemp(prefix="bench-localnet-")
    port0 = 28656

    def rpc(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/{path}", timeout=10) as r:
            return json.loads(r.read())

    procs = []
    try:
        subprocess.run(
            ["python", "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
             "--output-dir", root, "--chain-id", "bench-e2e",
             "--starting-port", str(port0)],
            check=True, capture_output=True, timeout=120)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for i in range(4):
            procs.append(subprocess.Popen(
                ["python", "-m", "tendermint_tpu.cmd", "--home",
                 f"{root}/node{i}", "start", "--log-level", "error"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        # wait for liveness
        deadline = time.time() + 120
        h0 = None
        while time.time() < deadline:
            try:
                h0 = int(rpc(port0 + 1, "status")
                         ["result"]["sync_info"]["latest_block_height"])
                if h0 >= 2:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert h0 is not None and h0 >= 2, "localnet failed to start"

        # measure block rate over a fixed window + tx commit latency
        t0 = time.time()
        start_h = int(rpc(port0 + 1, "status")
                      ["result"]["sync_info"]["latest_block_height"])
        tx_lat = []
        n_txs = 5
        for i in range(n_txs):
            body = json.dumps({
                "jsonrpc": "2.0", "id": 1, "method": "broadcast_tx_commit",
                "params": {"tx": __import__("base64").b64encode(
                    f"bench{i}=v{i}".encode()).decode()}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port0 + 1}/", data=body,
                headers={"Content-Type": "application/json"})
            t1 = time.time()
            with urllib.request.urlopen(req, timeout=30) as r:
                resp = json.loads(r.read())
            tx_lat.append(time.time() - t1)
            assert resp["result"]["deliver_tx"].get("code", 0) == 0
        elapsed = time.time() - t0
        end_h = int(rpc(port0 + 1, "status")
                    ["result"]["sync_info"]["latest_block_height"])
        blocks_per_min = (end_h - start_h) / elapsed * 60.0
        _emit("localnet_4node_tx_commit_latency_p50", float(np.median(tx_lat)),
              "s", 0.0)
        _emit("localnet_4node_blocks_per_min", blocks_per_min, "blocks/min",
              blocks_per_min / 19.5)
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)


def bench_verify_commit_10k():
    """FLAGSHIP (north star): VerifyCommit at 10,240 validators — the scale
    BASELINE.json names (≥15x target vs the host scalar loop, reference
    types/validator_set.go:667, docs/qa/v034). Two numbers:

    * sustained: a fast-sync-shaped stream of full commits in ONE
      batch_verify_stream call — internally segmented into ~10-chunk
      dispatches double-buffered on a worker thread, so segment i+1's host
      packing and host->device transfer overlap segment i's device compute
      (the relay serializes each dispatch, but a second thread's dispatch
      overlaps an in-flight one: measured 913 -> 510 ms on this workload);
    * one-shot: a single cold commit in one call, paying full dispatch
      latency (dominated by the relay's fixed cost on remote TPUs).

    Also prints a stage breakdown (pack / device+transfer) so regressions
    are attributable.
    """
    from tendermint_tpu import crypto
    from tendermint_tpu.crypto.ed25519_jax import verify as V

    n_vals, n_commits, window = 10240, 12, 12
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    commits = [_sign_commit(vs, keys, h, chain)[0]
               for h in range(1, n_commits + 1)]
    # flatten (pk, msg, sig) in valset order, per commit
    per_commit = []
    for c in commits:
        pks = [v.pub_key.bytes() for v in vs.validators]
        msgs = [c.vote_sign_bytes(chain, i) for i in range(n_vals)]
        sigs = [cs.signature for cs in c.signatures]
        per_commit.append((pks, msgs, sigs))

    def verify_window(cs):
        pks = [p for c in cs for p in c[0]]
        msgs = [m for c in cs for m in c[1]]
        sigs = [s for c in cs for s in c[2]]
        out = V.batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        assert out.all()

    def sustained():
        for i in range(0, n_commits, window):
            verify_window(per_commit[i:i + window])

    sustained()  # compile + warm the pk device cache
    best = _timed(sustained, warm=0, runs=3)
    total_sigs = n_commits * n_vals
    dev_rate = total_sigs / best

    # host scalar baseline on a subset
    pubs = [crypto.Ed25519PubKey(p) for p in per_commit[0][0][:N_BASE]]
    host_rate = _host_rate(pubs, per_commit[0][1], per_commit[0][2], N_BASE)

    # stage breakdown for the sustained path: host packing per pipeline
    # segment (2 commits = 10 chunks each, the segmented path's unit)
    t0 = time.perf_counter()
    for i in range(0, n_commits, 2):
        cs = per_commit[i:i + 2]
        V.prepare_sparse_stream([p for c in cs for p in c[0]],
                                [m for c in cs for m in c[1]],
                                [s for c in cs for s in c[2]], CHUNK)
    pack_s = time.perf_counter() - t0

    # one-shot: single commit, one call
    one = _timed(lambda: verify_window(per_commit[:1]), warm=1, runs=3)
    _emit("verify_commit_10k_oneshot_sigs_per_sec", n_vals / one, "sigs/s",
          (n_vals / one) / host_rate)
    _emit("verify_commit_10k_breakdown_pack_share", pack_s / best, "ratio",
          0.0, pack_seconds=round(pack_s, 3), total_seconds=round(best, 3))
    _emit("verify_commit_10k_sigs_per_sec", dev_rate, "sigs/s",
          dev_rate / host_rate)


CONFIGS = {
    "1": bench_stream,
    "2": bench_verify_commit_150,
    "3": bench_light_chain_1000,
    "4": bench_localnet,
    "5": bench_fast_sync_replay,
    "10k": bench_verify_commit_10k,
}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="all",
                    choices=list(CONFIGS) + ["all"],
                    help="BASELINE.json config; default runs every config, "
                         "flagship (10k) last")
    args = ap.parse_args()
    _enable_compile_cache()
    if args.config == "all":
        # flagship last: the driver records the final line. The remote
        # relay occasionally drops a compile mid-flight — retry each
        # config once before reporting it failed.
        for key in ("2", "3", "4", "5", "1", "10k"):
            for attempt in (1, 2):
                try:
                    CONFIGS[key]()
                    break
                except Exception as e:
                    if attempt == 2:
                        _emit(f"config_{key}_failed", 0.0, "error", 0.0,
                              error=f"{type(e).__name__}: {e}")
                    else:
                        time.sleep(5.0)
    else:
        CONFIGS[args.config]()
