"""Benchmark: VerifyCommit signature throughput, batched TPU path vs host scalar.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config #2/#3 of BASELINE.json: a synthetic 1024-signature commit batch
(vote sign-bytes identical in shape to types.Commit.vote_sign_bytes output).
Baseline = the host scalar loop (OpenSSL-backed PubKey.verify_signature, the
stand-in for the reference's Go x/crypto ed25519.Verify hot call at
crypto/ed25519/ed25519.go:148-155).
"""

import json
import time

import numpy as np


def build_batch(n: int):
    from tendermint_tpu import crypto
    from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    pks, msgs, sigs, pubs = [], [], [], []
    for i in range(n):
        priv = crypto.Ed25519PrivKey.generate(i.to_bytes(2, "big") * 16)
        # realistic vote sign-bytes (unique timestamp per validator)
        msg = vote_sign_bytes("bench-chain", SignedMsgType.PRECOMMIT, 100, 0,
                              bid, 1_700_000_000_000_000_000 + i)
        pub = priv.pub_key()
        pks.append(pub.bytes())
        msgs.append(msg)
        sigs.append(priv.sign(msg))
        pubs.append(pub)
    return pks, msgs, sigs, pubs


def main():
    n = 1024
    pks, msgs, sigs, pubs = build_batch(n)

    from tendermint_tpu.crypto.ed25519_jax import batch_verify

    # warmup: compile the kernel (cached across runs by jax platform cache)
    out = batch_verify(pks, msgs, sigs)
    assert np.asarray(out).all(), "warmup batch rejected valid sigs"

    # device path: best of 5 timed runs
    device_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = batch_verify(pks, msgs, sigs)
        device_times.append(time.perf_counter() - t0)
    assert np.asarray(out).all()
    device_sigs_per_sec = n / min(device_times)

    # host scalar baseline (the reference's one-verify-per-signature loop)
    t0 = time.perf_counter()
    ok = all(pub.verify_signature(m, s) for pub, m, s in zip(pubs, msgs, sigs))
    host_elapsed = time.perf_counter() - t0
    assert ok
    host_sigs_per_sec = n / host_elapsed

    print(json.dumps({
        "metric": "verify_commit_sigs_per_sec_batch1024",
        "value": round(device_sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(device_sigs_per_sec / host_sigs_per_sec, 3),
    }))


if __name__ == "__main__":
    main()
