"""Benchmarks: every BASELINE.md config, one JSON line each.

Output contract: each line is {"metric", "value", "unit", "vs_baseline"}.
The FLAGSHIP metric — sustained VerifyCommit throughput at 10,240
validators (the north star scale, reference types/validator_set.go:667) —
prints LAST so the driver records it.

Configs (BASELINE.json):
  1  Ed25519 batched stream, CHUNK-sig chunks scanned in one execution
  2  ValidatorSet.VerifyCommit over a 150-validator commit (one-shot)
  3  VerifyCommitLight+Trusting over a 1000-validator header chain
  4  4-node localnet (kvstore), consensus end-to-end blocks/min
  5  fast-sync windowed replay @ 1000 validators
  ingest  open-loop broadcast_tx load on the 4-node localnet: sustained
       committed txs/s + p99 broadcast->commit latency + p99 admission
       latency through the ingest fast path (tools/loadtime.py)
  multichip  devices x chunk scaling table (device_profile scale)
  10k  sustained VerifyCommit @ 10,240 validators (flagship, last) plus
       the multichip flagship through the multi-device dispatcher

Baselines: configs 1/2/3/5/10k measure the host scalar loop (OpenSSL-backed
PubKey.verify_signature — the stand-in for the reference's Go x/crypto
ed25519.Verify hot call, crypto/ed25519/ed25519.go:148-155) in the same
process. Config 4's baseline is the reference QA testnet's 19.5 blocks/min
(docs/qa/v034/README.md:141-142; 200-node WAN vs 4-node localhost — an
anchor, not an equal-hardware comparison).

The device path is charged end-to-end: host packing + transfer + kernel +
verdict fetch, exactly what the consensus/blocksync callers pay.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

N_STREAM = 32768
CHUNK = 2048
N_BASE = 2048


def _enable_compile_cache():
    try:
        from tendermint_tpu.libs.compilecache import enable_compile_cache

        warn = enable_compile_cache(
            os.path.join(os.path.dirname(__file__), ".jax_cache"))
        if warn:  # stderr: stdout is the driver-parsed JSONL stream
            print(warn, file=sys.stderr)
    except Exception:
        pass


def _emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(value, 3), "unit": unit,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def build_batch(n: int):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    rng = np.random.default_rng(7)
    pks, msgs, sigs, pubs = [], [], [], []
    for i in range(n):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub_bytes = priv.public_key().public_bytes_raw()
        # realistic vote sign-bytes (unique timestamp per validator)
        msg = vote_sign_bytes("bench-chain", SignedMsgType.PRECOMMIT, 100, 0,
                              bid, 1_700_000_000_000_000_000 + i)
        pks.append(pub_bytes)
        msgs.append(msg)
        sigs.append(priv.sign(msg))
        pubs.append(crypto.Ed25519PubKey(pub_bytes))
    return pks, msgs, sigs, pubs


def _host_rate(pubs, msgs, sigs, n: int) -> float:
    """Host scalar loop sigs/s on an n-item subset."""
    t0 = time.perf_counter()
    ok = all(pub.verify_signature(m, s)
             for pub, m, s in zip(pubs[:n], msgs[:n], sigs[:n]))
    elapsed = time.perf_counter() - t0
    assert ok
    return n / elapsed


def bench_stream():
    """Config #1: sustained batched-verifier throughput on vote sign-bytes."""
    pks, msgs, sigs, pubs = build_batch(N_STREAM)

    from tendermint_tpu.crypto.ed25519_jax import batch_verify_stream

    out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)  # compile
    assert np.asarray(out).all(), "warmup stream rejected valid sigs"
    times = []
    for _ in range(4):
        t0 = time.perf_counter()
        out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        times.append(time.perf_counter() - t0)
    assert np.asarray(out).all()
    dev = N_STREAM / min(times)
    host = _host_rate(pubs, msgs, sigs, N_BASE)
    _emit(f"verify_commit_sigs_per_sec_stream{CHUNK}", dev, "sigs/s",
          dev / host, chunk=CHUNK)


# --- commit helpers ---------------------------------------------------------

def _mk_val_set(n_vals: int, seed: int = 7):
    """A validator set + its signing keys (OpenSSL), reusable across heights."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet

    rng = np.random.default_rng(seed)
    keys = {}
    vals = []
    for _ in range(n_vals):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        keys[pub.address()] = sk
        vals.append(Validator(pub.address(), pub, 10))
    return ValidatorSet(vals), keys


def _sign_commit(vs, keys, height: int, chain_id: str):
    """A canonical commit for `height` signed by every validator, in
    validator-set order."""
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(hash(("bench", height)).to_bytes(8, "big", signed=True) * 4,
                  PartSetHeader(1, b"\x02" * 32))
    sigs = []
    for i, v in enumerate(vs.validators):
        ts = 1_700_000_000_000_000_000 + height * 1_000_000 + i
        msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, height, 0,
                              bid, ts)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts,
                              keys[v.address].sign(msg)))
    return Commit(height, 0, bid, sigs), bid


def _timed(fn, warm: int = 1, runs: int = 3) -> float:
    for _ in range(warm):
        fn()
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_verify_commit_150():
    """Config #2: ValidatorSet.VerifyCommit over a 150-validator commit
    (reference types/validator_set.go:667) — the live consensus hot loop.

    Two regimes:
    * seam cost: the auto backend vs the pinned host backend, interleaved
      A/B to cancel CPU drift — proves the routing seam costs nothing;
    * routing honesty: the auto router measured as-is. The calibrated
      break-even (crypto/batch.py device_threshold, payload-bearing probe)
      must keep a sub-threshold commit on the host path, so the routed
      number may never be slower than scalar — asserted, not just
      reported. (BENCH_r05 regression: a forced 16-sig threshold pushed
      this commit through the relay at 0.18x scalar.)
    """
    vs, keys = _mk_val_set(150)
    commit, bid = _sign_commit(vs, keys, 100, "bench-150")

    def run():
        vs.verify_commit("bench-150", bid, 100, commit)

    run()  # warm (sign-bytes memo, threshold calibration)
    dev_ts, host_ts = [], []

    def _one(pinned: bool) -> None:
        if pinned:
            os.environ["TMTPU_BATCH_BACKEND"] = "host"
        try:
            t0 = time.perf_counter()
            run()
            (host_ts if pinned else dev_ts).append(time.perf_counter() - t0)
        finally:
            if pinned:
                del os.environ["TMTPU_BATCH_BACKEND"]

    for i in range(9):  # interleaved A/B with alternating order: cache
        # warmth systematically favors whichever runs second in a pair
        _one(pinned=bool(i % 2))
        _one(pinned=not bool(i % 2))
    dev, host = min(dev_ts), min(host_ts)
    _emit("verify_commit_150_vals_sigs_per_sec", 150 / dev, "sigs/s",
          host / dev)

    # routed regime: the interleaved auto-backend measurement above IS the
    # calibrated router's decision (150 sigs below the break-even stays on
    # host; on locally-attached silicon, threshold ~16, the same call
    # routes to the device and must win there). Reusing the drift-cancelled
    # A/B numbers keeps the never-slower assertion symmetric — no separate
    # un-interleaved timing, no fudge factor.
    from tendermint_tpu.crypto.batch import device_threshold

    thr = device_threshold()
    not_slower = dev <= host * 1.05  # interleaved min-of-9 each; 5% jitter
    _emit("verify_commit_150_vals_device_routed_sigs_per_sec",
          150 / dev, "sigs/s", host / dev,
          calibrated_threshold=thr,
          routed_backend="jax" if 150 >= thr else "host",
          routing_not_slower_than_scalar=bool(not_slower))
    assert not_slower, (
        f"device routing slower than scalar: routed {150 / dev:.0f} "
        f"sigs/s vs host {150 / host:.0f} sigs/s (threshold {thr})")


def bench_light_chain_1000():
    """Config #3: light-client VerifyCommitLight+Trusting over a
    1000-validator header chain (reference validator_set.go:722,775,
    light/verifier.go:32). Device path: ONE segmented (pipelined) device
    call verifies every unique candidate signature across the 32-header
    range; both verification kinds then replay their scalar precedence
    semantics against the shared precomputed verdicts (the same dual-plane
    dedup the fast-sync reactor applies per window). Sign-bytes are built
    once per commit via the shared-field batch encoder. The metric's sig
    count is the UNIQUE signatures verified (n_headers x n_vals).

    vs_baseline is EQUAL WORK: the host baseline runs the identical dedup
    structure (one pass over unique signatures, scalar backend, then both
    replays) — a scalar implementation could memoize the same way, so the
    headline ratio credits only the crypto plane. This also approximates
    the reference's TRUE scalar cost: its early-exiting loops verify ~1001
    sigs/header (1/3 tally for trusting + 2/3 for light,
    validator_set.go:722,775) vs the 1000 unique here. The extra field
    vs_undeduped_scalar keeps round-over-round continuity with the r1-r4
    methodology, whose baseline pushed ALL candidates through the seam once
    per verification kind (~2x the unique set). (The helpers' own internal
    dispatch path is exercised by config #5's plane metric and the test
    suite.)"""
    from tendermint_tpu.types.validator_set import (
        verify_commit_light_batched,
        verify_commit_light_trusting_batched,
    )

    n_vals, n_headers = 1000, 32
    vs, keys = _mk_val_set(n_vals)
    commits = [_sign_commit(vs, keys, h, "bench-light")[0]
               for h in range(2, n_headers + 2)]
    trust = (1, 3)

    def _fresh_commits():
        # a real light client sees each commit once: drop the sign-bytes
        # memo so every timed pass pays construction, on both backends
        for c in commits:
            c.__dict__.pop("_sb_cache", None)

    def verify_chain_deduped(backend: str):
        from tendermint_tpu.crypto import batch as crypto_batch
        from tendermint_tpu.crypto.batch import (
            BatchVerifier,
            precomputed_verdicts,
        )

        _fresh_commits()
        # both verification kinds check the SAME candidate signatures, so
        # one verification pass serves trusting AND light (the same
        # dual-plane pattern the fast-sync reactor uses per window)
        bv = BatchVerifier(backend=backend)
        verdict_keys = []
        for c in commits:
            sb = c.vote_sign_bytes_all("bench-light")
            for idx, cs in enumerate(c.signatures):
                if cs.for_block():
                    pk = vs.validators[idx].pub_key
                    bv.add(pk, sb[idx], cs.signature)
                    verdict_keys.append((pk.bytes(), sb[idx], cs.signature))
        _, verdicts = bv.verify()
        token = precomputed_verdicts.set(
            {k: bool(v) for k, v in zip(verdict_keys, verdicts)})
        pre_before = crypto_batch.stats["precomputed_batches"]
        try:
            errs = verify_commit_light_trusting_batched(
                [(vs, "bench-light", c, trust) for c in commits])
            assert all(e is None for e in errs), errs
            errs = verify_commit_light_batched(
                [(vs, "bench-light", c.block_id, c.height, c)
                 for c in commits])
            assert all(e is None for e in errs), errs
        finally:
            precomputed_verdicts.reset(token)
        # guard the metric: a key mismatch would silently re-dispatch the
        # whole batch inside the timed region instead of replaying verdicts
        assert crypto_batch.stats["precomputed_batches"] == pre_before + 2, \
            "precomputed verdicts missed: bench would measure re-dispatch"

    def verify_chain_undeduped_host():
        _fresh_commits()
        for c in commits:
            vs.verify_commit_light_trusting("bench-light", c, trust)
            vs.verify_commit_light("bench-light", c.block_id, c.height, c)

    dev = _timed(lambda: verify_chain_deduped("jax"))
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        # equal work: the SAME dedup structure on the scalar backend
        host = _timed(lambda: verify_chain_deduped("host"), warm=0, runs=1)
        # the reference-shaped seam: each kind verifies its candidates
        host2x = _timed(verify_chain_undeduped_host, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    # unique candidate signatures verified per pass (the honest numerator:
    # both verification kinds share the same signatures, verified once)
    sigs = n_headers * n_vals
    _emit("light_chain_1000_vals_sigs_per_sec", sigs / dev, "sigs/s",
          host / dev, vs_undeduped_scalar=round(host2x / dev, 3))


def bench_fast_sync_replay():
    """Config #5 (scaled): the block-sync engine's windowed batched commit
    verification over a 1000-validator chain (reference
    blockchain/v0/reactor.go:255; our blockchain/reactor.py). Measures
    the verification plane, which is the reference's fast-sync bottleneck."""
    from tendermint_tpu.types.validator_set import verify_commit_light_batched

    n_vals, n_blocks, window = 1000, 64, 16
    vs, keys = _mk_val_set(n_vals)
    entries = []
    for h in range(1, n_blocks + 1):
        commit, bid = _sign_commit(vs, keys, h, "bench-sync")
        entries.append((vs, "bench-sync", bid, h, commit))

    def replay():
        for i in range(0, n_blocks, window):
            errs = verify_commit_light_batched(entries[i:i + window])
            assert all(e is None for e in errs), errs

    dev = _timed(replay)
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(replay, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    _emit("fast_sync_1000_vals_blocks_per_sec", n_blocks / dev, "blocks/s",
          host / dev)
    bench_fast_sync_pipeline()


def bench_fast_sync_pipeline():
    """Config #5 (pipeline): END-TO-END fast-sync replay — real blocks
    through the real BlockchainReactor window loop (verify both signature
    planes in one batched device scope) + BlockExecutor.ApplyBlock (kvstore
    ABCI app, local client) + BlockStore/StateStore writes. 256 blocks @
    1000 validators, measured as a fresh node syncing the chain; the host
    baseline replays a 64-block prefix through the identical loop with the
    scalar backend. Reference blockchain/v0/reactor.go:255 + BASELINE.md
    config #5."""
    import asyncio

    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.blockchain import BlockchainReactor, BlockPool
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
    from tendermint_tpu.state.execution import EmptyEvidencePool, NoOpMempool
    from tendermint_tpu.store import BlockStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.canonical import vote_sign_bytes_batch

    n_vals, n_blocks = 1000, 256
    chain_id = "bench-sync-pipe"
    vs, keys = _mk_val_set(n_vals)
    genesis = GenesisDoc(
        chain_id=chain_id, genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(v.pub_key, v.voting_power)
                    for v in vs.validators])

    build_verdicts: dict = {}  # (pk, sb, sig) -> True, for setup-time skip

    def sign_seen_commit(state, block, bid):
        ts = block.header.time_ns + 1
        sbs = vote_sign_bytes_batch(
            chain_id, SignedMsgType.PRECOMMIT, block.header.height, 0,
            [bid] * n_vals, [ts] * n_vals)
        sigs = []
        for v, sb in zip(state.validators.validators, sbs):
            sig = keys[v.address].sign(sb)
            sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts, sig))
            build_verdicts[(v.pub_key.bytes(), sb, sig)] = True
        return Commit(block.header.height, 0, bid, sigs)

    def fresh_node():
        app = KVStoreApplication()
        conns = AppConns(local_client_creator(app))
        conns.start()
        state = state_from_genesis(genesis)
        state_store = StateStore(MemDB())
        state_store.save(state)
        block_store = BlockStore(MemDB())
        execu = BlockExecutor(state_store, conns.consensus, NoOpMempool(),
                              EmptyEvidencePool(), block_store)
        return state, execu, block_store, conns

    # build the source chain once (n_blocks + 1 so every block has a
    # successor carrying its seen commit). Setup only: our own fresh
    # signatures are known-valid, so apply_block's LastCommit re-check runs
    # against precomputed verdicts instead of a per-block device dispatch.
    from tendermint_tpu.crypto.batch import precomputed_verdicts

    state, execu, _bs, conns = fresh_node()
    blocks = []
    last_commit = Commit(0, 0, BlockID(), [])
    token = precomputed_verdicts.set(build_verdicts)
    try:
        for h in range(1, n_blocks + 2):
            proposer = state.validators.get_proposer().address
            block, parts = state.make_block(h, [f"h{h}=v".encode()],
                                            last_commit, [], proposer)
            bid = BlockID(block.hash(), parts.header())
            blocks.append(block)
            state, _ = execu.apply_block(state, bid, block)
            last_commit = sign_seen_commit(state, block, bid)
    finally:
        precomputed_verdicts.reset(token)
    conns.stop()

    def replay(n):
        state, execu, block_store, conns = fresh_node()
        try:
            for b in blocks:  # fresh node: none of the per-instance memos a
                # previous replay populated (sign-bytes, part sets, header
                # hashes) may leak into this pass — the host baseline must
                # pay the same hashing work the timed run paid
                b.last_commit.__dict__.pop("_sb_cache", None)
                b.__dict__.pop("_part_set_cache", None)
                b.header.__dict__.pop("_hash_memo", None)
            reactor = BlockchainReactor(state, execu, block_store,
                                        fast_sync=True)
            reactor.pool = BlockPool(1)
            reactor.pool.set_peer_range("src", 1, n + 1)

            async def drive():
                # keep TWO full verify windows downloaded before each
                # process call: the apply pipeline prepares window N+1 on a
                # worker thread while window N applies, and needs N+1's
                # blocks present at spawn time (n is a multiple of the
                # reactor's VERIFY_WINDOW=16, so no ragged tail window)
                while reactor.blocks_synced < n:
                    want = min(33, n + 2 - reactor.pool.height)
                    while len(reactor.pool.peek_window(33)) < want:
                        reqs = reactor.pool.schedule_requests()
                        if not reqs:
                            break
                        for pid, h in reqs:
                            reactor.pool.add_block(pid, blocks[h - 1])
                    before = reactor.blocks_synced
                    await reactor._process_window()
                    assert reactor.blocks_synced > before, \
                        f"sync stalled at {before}"
                assert reactor.state.last_block_height >= n

            asyncio.run(drive())
            assert block_store.height() >= n
            return reactor
        finally:
            conns.stop()

    replay(32)  # warm: compile shapes, device pk cache
    t0 = time.perf_counter()
    reactor = replay(n_blocks)
    dev = time.perf_counter() - t0
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        t0 = time.perf_counter()
        replay(64)
        host_rate = 64 / (time.perf_counter() - t0)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    rate = n_blocks / dev
    st = reactor.stage_breakdown()  # derived from BlocksyncMetrics histograms
    assert st["pipelined_windows"] > 0, \
        "apply pipeline never engaged: every window was prepared inline"
    # hash+store share of end-to-end pipeline wall-clock: the two apply-plane
    # costs this round attacked directly (iterative merkle + hash
    # memoization; per-window write batches). verify_s runs on the worker
    # thread overlapped with apply, so stage shares can sum past 1.0.
    _emit("fast_sync_pipeline_breakdown_hash_store_share",
          (st["hash_s"] + st["store_s"]) / dev, "ratio", 0.0,
          hash_seconds=round(st["hash_s"], 3),
          store_seconds=round(st["store_s"], 3),
          verify_seconds=round(st["verify_s"], 3),
          abci_seconds=round(st["abci_s"], 3),
          wall_seconds=round(dev, 3),
          pipelined_windows=st["pipelined_windows"],
          inline_windows=st["inline_windows"])
    _emit("fast_sync_1000_vals_pipeline_blocks_per_sec", rate, "blocks/s",
          rate / host_rate)


#: previous round's localnet p50 commit latency (BENCH_r05) — the anchor the
#: live-plane work is measured against (this PR's event-driven gossip + WAL
#: group commit target exactly this number)
R05_LOCALNET_P50_S = 1.121


def _prom_sum(text: str, name: str) -> float:
    """Sum a Prometheus series across its label sets (text exposition)."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue  # e.g. foo_sum when asked for foo
        try:
            total += float(line.rsplit(" ", 1)[1])
        except ValueError:
            pass
    return total


def _prom_by_label(text: str, name: str, label: str) -> dict:
    """{label value: series value} for one labeled series (exposition
    text), e.g. per-stage sums of the consensus stage histogram."""
    out = {}
    needle = f'{label}="'
    for line in text.splitlines():
        if not line.startswith(name + "{"):
            continue
        rest = line[len(name):]
        i = rest.find(needle)
        if i < 0:
            continue
        val = rest[i + len(needle):]
        val = val[:val.index('"')]
        try:
            out[val] = out.get(val, 0.0) + float(line.rsplit(" ", 1)[1])
        except ValueError:
            pass
    return out


def _tools_mod(name: str):
    """Import a stdlib-only module out of tools/ (trace_summary,
    fleet_scrape, trace_merge) without making tools a package."""
    from tendermint_tpu.libs.toolbox import load_tool

    return load_tool(name)


def bench_localnet():
    """Config #4: 4-node localnet over TCP (kvstore app), consensus reactor
    end-to-end. Measures blocks/min across the net and broadcast_tx_commit
    latency, plus the live-plane breakdown (gossip wakeups vs polls,
    encode-cache hit rate, WAL records-per-fsync) scraped from /metrics and
    a per-height span breakdown from the nodes' shutdown traces. Baseline
    anchors: reference 200-node QA testnet 19.5 blocks/min
    (docs/qa/v034/README.md:141-142); p50 latency vs BENCH_r05's 1.121 s."""
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile
    import urllib.request

    root = tempfile.mkdtemp(prefix="bench-localnet-")
    port0 = 28656

    def rpc(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/{path}", timeout=10) as r:
            return json.loads(r.read())

    procs = []
    per_height = None
    fleet = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # CPU-pinned subprocesses (init included) must not touch the TPU
        # relay: the axon plugin registers at interpreter startup
        # (sitecustomize) and a slow relay would stall startup past the
        # liveness deadline (the e2e runner drops this var the same way)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        # each node runs under the span tracer and writes a Chrome trace on
        # graceful shutdown — the per-height live-plane attribution input
        env["TMTPU_TRACE_OUT"] = os.path.join(root, "trace")
        # a watchdog debugdump during the run snapshots the fleet rollup
        env["TMTPU_FLEET_JSON"] = os.path.join(root, "fleet.json")
        subprocess.run(
            ["python", "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
             "--output-dir", root, "--chain-id", "bench-e2e",
             "--starting-port", str(port0), "--prometheus"],
            check=True, capture_output=True, timeout=120, env=env)
        for i in range(4):
            procs.append(subprocess.Popen(
                ["python", "-m", "tendermint_tpu.cmd", "--home",
                 f"{root}/node{i}", "start", "--log-level", "error"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        # wait for liveness
        deadline = time.time() + 120
        h0 = None
        while time.time() < deadline:
            try:
                h0 = int(rpc(port0 + 1, "status")
                         ["result"]["sync_info"]["latest_block_height"])
                if h0 >= 2:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert h0 is not None and h0 >= 2, "localnet failed to start"

        # fleet metrics aggregator (tools/fleet_scrape.py): poll all four
        # nodes' /metrics during the measurement window so the reported
        # numbers are cluster truth, not node-0's view
        try:
            fs = _tools_mod("fleet_scrape")
            fleet = fs.FleetScraper(
                {f"node{i}": f"http://127.0.0.1:{port0 + 8 + i}/metrics"
                 for i in range(4)},
                interval_s=2.0,
                out_path=os.path.join(root, "fleet.json")).start()
        except Exception:
            fleet = None

        # measure block rate over a fixed window + tx commit latency
        t0 = time.time()
        start_h = int(rpc(port0 + 1, "status")
                      ["result"]["sync_info"]["latest_block_height"])
        tx_lat = []
        n_txs = 5
        for i in range(n_txs):
            body = json.dumps({
                "jsonrpc": "2.0", "id": 1, "method": "broadcast_tx_commit",
                "params": {"tx": __import__("base64").b64encode(
                    f"bench{i}=v{i}".encode()).decode()}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port0 + 1}/", data=body,
                headers={"Content-Type": "application/json"})
            t1 = time.time()
            with urllib.request.urlopen(req, timeout=30) as r:
                resp = json.loads(r.read())
            tx_lat.append(time.time() - t1)
            assert resp["result"]["deliver_tx"].get("code", 0) == 0
        elapsed = time.time() - t0
        end_h = int(rpc(port0 + 1, "status")
                    ["result"]["sync_info"]["latest_block_height"])
        blocks_per_min = (end_h - start_h) / elapsed * 60.0
        p50 = float(np.median(tx_lat))
        _emit("localnet_4node_tx_commit_latency_p50", p50, "s",
              R05_LOCALNET_P50_S / p50, r05_p50_s=R05_LOCALNET_P50_S)
        _emit("localnet_4node_blocks_per_min", blocks_per_min, "blocks/min",
              blocks_per_min / 19.5)

        # live-plane breakdown from the RPC node's (node0's) /metrics —
        # testnet --prometheus serves node i on starting_port+2v+i (past the
        # p2p/rpc port block), and every rpc()/tx call above hit node0 (rpc
        # port port0+1)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port0 + 8}/metrics", timeout=10) as r:
                mtext = r.read().decode()
            pre = "tendermint_consensus_"
            wakeups = _prom_sum(mtext, pre + "gossip_wakeups_total")
            polls = _prom_sum(mtext, pre + "gossip_polls_total")
            ehits = _prom_sum(mtext, pre + "encode_cache_hits_total")
            emiss = _prom_sum(mtext, pre + "encode_cache_misses_total")
            fsyncs = _prom_sum(mtext, pre + "wal_fsyncs_total")
            rec_sum = _prom_sum(mtext, pre + "wal_records_per_fsync_sum")
            rec_cnt = _prom_sum(mtext, pre + "wal_records_per_fsync_count")
            fsync_s = _prom_sum(mtext, pre + "wal_fsync_seconds_sum")
            _emit("localnet_4node_live_plane_breakdown",
                  wakeups / max(1.0, wakeups + polls), "ratio", 0.0,
                  gossip_wakeups=int(wakeups), gossip_polls=int(polls),
                  encode_cache_hits=int(ehits),
                  encode_cache_misses=int(emiss),
                  encode_cache_hit_ratio=round(
                      ehits / max(1.0, ehits + emiss), 3),
                  wal_fsyncs=int(fsyncs),
                  wal_records_per_fsync_avg=round(
                      rec_sum / max(1.0, rec_cnt), 2),
                  wal_fsync_seconds_total=round(fsync_s, 4))
            # per-stage consensus latency decomposition from the stage
            # timeline histograms (consensus/timeline.py): mean seconds per
            # stage interval at this node — the bench row the ROADMAP scale
            # items will attribute regressions through
            s_sum = _prom_by_label(mtext, pre + "stage_seconds_sum", "stage")
            s_cnt = _prom_by_label(mtext, pre + "stage_seconds_count",
                                   "stage")
            stage_mean_ms = {
                s: round(s_sum[s] / s_cnt[s] * 1000.0, 3)
                for s in sorted(s_sum) if s_cnt.get(s)}
            if stage_mean_ms:
                _emit("localnet_4node_stage_breakdown",
                      sum(stage_mean_ms.values()) / 1000.0, "s", 0.0,
                      stage_mean_ms=stage_mean_ms,
                      heights_observed=int(max(s_cnt.values())))
        except Exception as e:
            _emit("localnet_4node_live_plane_breakdown", 0.0, "error", 0.0,
                  error=f"{type(e).__name__}: {e}")

        # cluster rollup: blocks/min as the CLUSTER saw it (max committed
        # height across nodes), gossip wakeups per peer link, and the
        # cross-node spread of committed heights at the last scrape
        if fleet is not None:
            try:
                roll = fleet.stop()
                fleet = None
                hs = roll["series"].get(
                    "tendermint_consensus_committed_height", {})
                _emit("localnet_4node_cluster_rollup",
                      roll.get("cluster_blocks_per_min", 0.0), "blocks/min",
                      roll.get("cluster_blocks_per_min", 0.0) / 19.5,
                      n_nodes=roll["n_nodes"],
                      scrapes=roll["scrapes"],
                      scrape_errors=roll["scrape_errors"],
                      height_min=hs.get("min"), height_max=hs.get("max"),
                      wakeups_per_peer_link=roll.get(
                          "wakeups_per_peer_link", 0.0))
            except Exception as e:
                _emit("localnet_4node_cluster_rollup", 0.0, "error", 0.0,
                      error=f"{type(e).__name__}: {e}")
    finally:
        if fleet is not None:  # a failed run must not leak the scraper
            try:
                fleet.stop()
            except Exception:
                pass
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        # per-height live-plane attribution from the nodes' shutdown traces
        # (gossip wait vs WAL sync vs apply vs consensus stage_* spans per
        # height) — best-effort
        skew = None
        trace_paths = []
        try:
            trace_summary = _tools_mod("trace_summary")
            by_height = trace_summary.by_height
            load_events = trace_summary.load_events
            merged = {}
            trace_paths = [os.path.join(root, name)
                           for name in sorted(os.listdir(root))
                           if name.startswith("trace-")
                           and name.endswith(".json")]
            for path in trace_paths:
                for h, per in by_height(load_events(path)).items():
                    tgt = merged.setdefault(h, {})
                    for span, us in per.items():
                        tgt[span] = tgt.get(span, 0.0) + us
            if merged:
                spans = sorted({s for per in merged.values() for s in per})
                n_h = len(merged)
                mean_ms = {s: round(sum(per.get(s, 0.0)
                                        for per in merged.values())
                                    / n_h / 1000.0, 3) for s in spans}
                per_height = {"n_heights": n_h, "mean_ms_per_height": mean_ms}
        except Exception:
            per_height = None
        # cross-node correlation: merge the four traces onto one wall
        # clock (tools/trace_merge.py) and report the commit skew —
        # first-to-last commit spread per height across nodes. Own
        # try/except: a torn trace from a SIGKILLed node must not wipe
        # the per-height breakdown computed above.
        try:
            if len(trace_paths) >= 2:
                tm = _tools_mod("trace_merge")
                docs = []
                for p in trace_paths:
                    doc = tm.load_trace(p)
                    docs.append((tm.node_label(doc, p), doc))
                report = tm.skew_report(docs)
                if report["heights"]:
                    skew = {"heights": report["heights"],
                            "mean_spread_ms": report["mean_spread_ms"],
                            "max_spread_ms": report["max_spread_ms"],
                            "slowest_stage_per_node": {
                                n: s["slowest_stage"] for n, s in
                                report["slowest_stage_per_node"].items()}}
        except Exception:
            skew = None
        shutil.rmtree(root, ignore_errors=True)
    if per_height is not None:
        _emit("localnet_4node_per_height_breakdown",
              per_height["mean_ms_per_height"].get("gossip_idle", 0.0),
              "ms/height", 0.0, **per_height)
    if skew is not None:
        _emit("localnet_4node_commit_skew", skew["mean_spread_ms"],
              "ms/height", 0.0, **skew)


def bench_ingest():
    """Config ingest: open-loop broadcast_tx load against the 4-node
    localnet (tools/loadtime.py) — the ROADMAP ingestion plane's gate.
    Send times are pre-planned on a fixed-rate grid (coordinated omission
    cannot hide stalls); per-tx latency is recovered from committed blocks
    via the embedded planned-send timestamp, cross-checked against the
    nodes' own /tx_timeline lifecycle records; mempool/RPC ingestion
    series ride along from node0's /metrics. Emits three gated rows:
    localnet_4node_ingest_txs_per_sec (higher-better),
    localnet_4node_ingest_commit_latency_p99_s (lower-better), and
    localnet_4node_ingest_checktx_p99_s (lower-better admission latency,
    rpc_received→mempool_admitted measured in-node by txlife)."""
    import asyncio
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.request

    root = tempfile.mkdtemp(prefix="bench-ingest-")
    port0 = 28856  # clear of config 4's 28656 block when running "all"
    # 150 tx/s: 6x the PR 11 smoke rate — a load the pre-lane scalar
    # admission path was never shown to sustain; the sharded-lane +
    # async-admission fast path must hold it with p99 commit latency no
    # worse (both rows gated in bench_compare, plus admission p99 below)
    rate, duration, size, clients = 150.0, 12.0, 96, 8
    endpoint = f"http://127.0.0.1:{port0 + 1}"
    metrics_endpoint = f"http://127.0.0.1:{port0 + 8}/metrics"

    def rpc(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/{path}", timeout=10) as r:
            return json.loads(r.read())

    def emit_error(err: str) -> None:
        # the crashed-config unit convention: both gated rows must read
        # as ERRORED in bench_compare, never as silent absence
        for metric in ("localnet_4node_ingest_txs_per_sec",
                       "localnet_4node_ingest_commit_latency_p99_s",
                       "localnet_4node_ingest_checktx_p99_s"):
            _emit(metric, 0.0, "error", 0.0, error=err)

    procs = []
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        subprocess.run(
            ["python", "-m", "tendermint_tpu.cmd", "testnet", "--v", "4",
             "--output-dir", root, "--chain-id", "bench-ingest",
             "--starting-port", str(port0), "--prometheus"],
            check=True, capture_output=True, timeout=120, env=env)
        for i in range(4):
            procs.append(subprocess.Popen(
                ["python", "-m", "tendermint_tpu.cmd", "--home",
                 f"{root}/node{i}", "start", "--log-level", "error"],
                env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.time() + 120
        h0 = None
        while time.time() < deadline:
            try:
                h0 = int(rpc(port0 + 1, "status")
                         ["result"]["sync_info"]["latest_block_height"])
                if h0 >= 2:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert h0 is not None and h0 >= 2, "localnet failed to start"

        lt = _tools_mod("loadtime")
        load_stats = asyncio.run(lt.open_loop_load(
            endpoint, rate=rate, duration=duration, size=size,
            clients=clients))
        # settle: let the tail of the offered load commit before reading
        # the chain back (bounded — a wedged net must not hang the bench)
        settle_deadline = time.time() + 30
        while time.time() < settle_deadline:
            try:
                pending = int(rpc(port0 + 1, "num_unconfirmed_txs")
                              ["result"]["n_txs"])
                if pending == 0:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        doc = lt.report_doc(endpoint, metrics_endpoint=metrics_endpoint)
        if not doc.get("txs"):
            emit_error("no harness txs found in committed blocks")
            return
        tlr = doc.get("tx_timeline", {})
        mtx = doc.get("metrics", {})
        _emit("localnet_4node_ingest_txs_per_sec", doc["txs_per_sec"],
              "txs/s", doc["txs_per_sec"] / rate,
              offered_rate=rate, duration_s=duration, clients=clients,
              planned=load_stats["planned"],
              accepted=load_stats["accepted"],
              rejected=load_stats["rejected"],
              send_errors=load_stats["errors"],
              committed=doc["txs"],
              max_sched_lag_s=round(load_stats["max_sched_lag_s"], 4),
              mempool_admitted=mtx.get(
                  "tendermint_mempool_admitted_txs_total"),
              rpc_broadcast_ok=mtx.get(
                  'tendermint_rpc_request_seconds_count'
                  '{endpoint="broadcast_tx_sync",outcome="ok"}'))
        # the acceptance probe: at least one sampled tx's timeline record
        # must carry the full rpc_received → committed stage chain
        _emit("localnet_4node_ingest_commit_latency_p99_s",
              doc["latency_s"]["p99"], "s", 0.0,
              latency_s=doc["latency_s"],
              node_commit_latency_s=tlr.get("node_commit_latency_s"),
              timeline_complete_records=tlr.get(
                  "complete_rpc_to_commit_records"),
              timeline_stage_counts=tlr.get("stage_counts"),
              timeline_sampled_sealed=tlr.get("sealed_total"))
        # admission latency (rpc_received → mempool_admitted measured IN
        # node0 by txlife): the async admission path's own cost, gated
        # lower-better so intake-queue/batching regressions trip loudly
        adm = tlr.get("admission_latency_s") or {}
        if "p99" not in adm:
            _emit("localnet_4node_ingest_checktx_p99_s", 0.0, "error", 0.0,
                  error="no timeline records carried "
                        "rpc_received+mempool_admitted marks")
        else:
            _emit("localnet_4node_ingest_checktx_p99_s", adm["p99"],
                  "s", 0.0, admission_latency_s=adm,
                  rejections=doc.get("rejections"))
    except Exception as e:
        emit_error(f"{type(e).__name__}: {e}")
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        shutil.rmtree(root, ignore_errors=True)


def bench_churn():
    """Config churn: the membership-churn plane, measured (tools/churn.py
    in-proc rig — no subprocess fleet, so it runs in slim containers).

    Gated rows, from a seeded N=8 run (one statesync join + one clean
    leave per interval under open-loop load, the validator set rotating
    across app-driven prune boundaries):
    * inproc_churn8_blocks_per_min   — liveness under churn (higher better)
    * inproc_churn8_join_caughtup_s  — worst join-to-caught-up (lower
      better): launch → snapshot restore over the wire → fast-sync →
      caught up to the net's height at entry

    Informational scaling row: gossip wakeups per directed peer-link per
    block on static SPARSE fleets at N=8/16/32 — per-link wakeups staying
    flat as the fleet quadruples is the evidence that the wire-encode
    cache + event-driven gossip keep cost sublinear in peer count (each
    node pays for its degree, not the fleet)."""
    churn = _tools_mod("churn")

    try:
        rep = churn.run_churn(n_nodes=8, intervals=2, seed=1)
        joins = rep["join_caughtup_s"]
        _emit("inproc_churn8_blocks_per_min", rep["blocks_per_min"],
              "blocks/min", rep["blocks_per_min"] / 19.5,
              height_span=[rep["height_initial"], rep["height_final"]],
              rotations=rep["rotations"],
              executed=[list(e) for e in rep["executed"]],
              topology=rep["topology"])
        _emit("inproc_churn8_join_caughtup_s",
              max(joins.values()), "s", 0.0, per_join=joins,
              prune_floor=rep["prune_floor"])
    except Exception as e:
        err = f"{type(e).__name__}: {e}"
        _emit("inproc_churn8_blocks_per_min", 0.0, "error", 0.0, error=err)
        _emit("inproc_churn8_join_caughtup_s", 0.0, "error", 0.0, error=err)

    try:
        cells = {}
        for n in (8, 16, 32):
            cells[str(n)] = churn.measure_gossip(n=n, blocks=3,
                                                 topology="sparse",
                                                 degree=4, seed=1)
        w8 = cells["8"]["wakeups_per_link_per_s"]
        w32 = cells["32"]["wakeups_per_link_per_s"]
        # sublinear: the per-link wakeup RATE may wobble but must not
        # scale with the 4x fleet growth (2x headroom for scheduler
        # noise); per-BLOCK numbers are in the cells for context but
        # don't gate — block cadence itself slows with N
        _emit("inproc_churn_gossip_scaling_breakdown",
              w32 / max(0.001, w8), "ratio", 0.0,
              cells=cells, sublinear=bool(w32 <= 2.0 * max(0.001, w8)))
    except Exception as e:
        _emit("inproc_churn_gossip_scaling_breakdown", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")


def bench_crash():
    """Config crash: crash recovery, measured (tools/crashmatrix.py in-proc
    rig — no subprocess fleet, so it runs in slim containers).

    Gated row, from a seeded 4-validator run where the persistent victim
    is SIGKILL-equivalently killed at two representative durability
    boundaries (post-WAL-fsync and mid-window-flush) and supervisor-
    restarted from its home dir (WAL repair-on-open + handshake replay +
    WAL catchup replay + FilePV reload + consensus catchup):

    * inproc_crash4_kill_caughtup_s — WORST kill→caught-up seconds (lower
      better): arm boundary → victim dies at it → bounded backoff →
      rebuild → height >= the net's tip. The recovery-time budget the
      ROADMAP's real-fleet milestones inherit.

    The full boundary matrix (10 boundaries, double-sign/evidence/
    mempool-WAL invariants, --verify-determinism) runs as the crashmatrix
    tool + the slow test tier; the bench keeps the fast, gateable core."""
    cm = _tools_mod("crashmatrix")

    try:
        rep = cm.run_matrix(seed=1, boundaries=["wal.after_fsync",
                                                "db.mid_window_flush"])
        per = {k["boundary"]: k["kill_to_caughtup_s"] for k in rep["kills"]}
        _emit("inproc_crash4_kill_caughtup_s",
              max(per.values()), "s", 0.0, per_boundary=per,
              restarts=sum(k["restarts"] for k in rep["kills"]),
              wal_repaired=[k["boundary"] for k in rep["kills"]
                            if k.get("wal_repaired")],
              mempool_wal_idempotent=rep["mempool_wal_idempotent"],
              boundaries_killed=rep["boundaries_killed"])
    except Exception as e:
        # the crashed-config unit convention: the gated row must read
        # "errored", never silently vanish
        _emit("inproc_crash4_kill_caughtup_s", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")


def bench_verify_commit_10k():
    """FLAGSHIP (north star): VerifyCommit at 10,240 validators — the scale
    BASELINE.json names (≥15x target vs the host scalar loop, reference
    types/validator_set.go:667, docs/qa/v034). Two numbers:

    * sustained: a fast-sync-shaped stream of full commits in ONE
      batch_verify_stream call — internally segmented into ~10-chunk
      dispatches double-buffered on a worker thread, so segment i+1's host
      packing and host->device transfer overlap segment i's device compute
      (the relay serializes each dispatch, but a second thread's dispatch
      overlaps an in-flight one: measured 913 -> 510 ms on this workload);
    * one-shot: a single cold commit in one call, paying full dispatch
      latency (dominated by the relay's fixed cost on remote TPUs).

    Also prints a stage breakdown (pack / device+transfer) so regressions
    are attributable.
    """
    from tendermint_tpu import crypto
    from tendermint_tpu.crypto.ed25519_jax import verify as V

    n_vals, n_commits, window = 10240, 12, 12
    repeats = 5
    vs, keys = _mk_val_set(n_vals)
    chain = "bench-10k"
    pks_row = [v.pub_key.bytes() for v in vs.validators]

    def build_slice(base_h):
        """A fresh n_commits batch signed at disjoint heights: every repeat
        gets distinct sign-bytes AND signatures, so the relay's
        identical-computation cache cannot serve a previous repeat's run
        and inflate the min-of-N."""
        per_commit = []
        for h in range(base_h, base_h + n_commits):
            c = _sign_commit(vs, keys, h, chain)[0]
            per_commit.append((pks_row, c.vote_sign_bytes_all(chain),
                               [cs.signature for cs in c.signatures]))
        return per_commit

    def verify_window(cs):
        pks = [p for c in cs for p in c[0]]
        msgs = [m for c in cs for m in c[1]]
        sigs = [s for c in cs for s in c[2]]
        out = V.batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        assert out.all()

    def sustained(per_commit):
        for i in range(0, n_commits, window):
            verify_window(per_commit[i:i + window])

    from tendermint_tpu.crypto import phases

    warm_pc = build_slice(1)
    sustained(warm_pc)  # compile + warm the pk device cache
    # min-of-5 with FRESH inputs per repeat: the relay's effective bandwidth
    # swings 2-4x hour to hour, but its cache must never turn a repeat into
    # a no-op; per-repeat values land in the JSON for auditability
    repeat_times, repeat_marks = [], []
    for rep in range(repeats):
        pc = build_slice(1000 + rep * n_commits)  # untimed setup
        t0 = time.perf_counter()
        sustained(pc)
        t1 = time.perf_counter()
        repeat_times.append(t1 - t0)
        repeat_marks.append((t0, t1))
        del pc
    best_i = int(np.argmin(repeat_times))
    best = repeat_times[best_i]
    total_sigs = n_commits * n_vals
    dev_rate = total_sigs / best

    # host scalar baseline on a subset
    pubs = [crypto.Ed25519PubKey(p) for p in warm_pc[0][0][:N_BASE]]
    host_rate = _host_rate(pubs, warm_pc[0][1], warm_pc[0][2], N_BASE)

    # stage breakdown from the dispatcher's OWN phase telemetry
    # (crypto/phases.py): the per-segment pack/dispatch/fetch stamps
    # recorded during the best timed repeat, decomposed by interval union —
    # no more hand-placed perf_counter pair re-packing outside the run
    w0, w1 = repeat_marks[best_i]
    recs = [r for r in phases.recent_segments()
            if r["t0"] >= w0 and r["t_end"] <= w1 + 1e-6]
    bd = phases.phase_breakdown(recs, w0, w1) if recs else None

    # one-shot: single cold commit, one call — three DISTINCT commits so
    # the relay cache can't serve run 2 and 3 from run 1
    oneshot_pc = build_slice(5000)[:3]
    one = min(_timed(lambda c=c: verify_window([c]), warm=0, runs=1)
              for c in oneshot_pc)
    _emit("verify_commit_10k_oneshot_sigs_per_sec", n_vals / one, "sigs/s",
          (n_vals / one) / host_rate)
    if bd is not None:
        # gated lower-is-better by tools/bench_compare.py (the 7% -> 11.1%
        # r04->r05 packing creep ran ungated): total pack seconds across
        # all pipeline threads over the best repeat's wall
        _emit("verify_commit_10k_breakdown_pack_share",
              bd["pack_share_total"], "ratio", 0.0,
              pack_seconds=round(bd["pack_s"], 3),
              total_seconds=round(best, 3),
              segments=bd["segments"], source="phase_telemetry")
        # per-phase wall decomposition: exposed pack + exposed dispatch +
        # device-in-flight union tile the wall, so their sum (the accounted
        # share) must come within 10% of end-to-end wall time — the
        # telemetry indicting itself if a phase goes missing
        acc = bd["accounted_share"]
        # an accounting shortfall (>10% of wall unattributed) means a
        # dispatch phase is going unrecorded — flag it with the crashed-
        # config unit convention so bench_compare surfaces it, but never
        # abort the run over an environment-dependent accounting gap
        _emit("verify_commit_10k_phase_shares", acc,
              "ratio" if acc >= 0.90 else "error", 0.0,
              pack_share=round(bd["pack_share_exposed"], 3),
              dispatch_share=round(bd["dispatch_share_exposed"], 3),
              device_share=round(bd["device_share"], 3),
              pack_share_total=round(bd["pack_share_total"], 3),
              overlap_ratio=round(bd["overlap_ratio"], 3),
              fetch_wait_seconds=round(bd["wait_s"], 3),
              segments=bd["segments"],
              accounted_within_10pct=bool(acc >= 0.90))
    else:
        _emit("verify_commit_10k_breakdown_pack_share", 0.0, "error", 0.0,
              error="no phase records captured during the timed repeats")
    # multichip flagship: the same windows through the multi-device
    # dispatcher (which the routed flagship above already rides when >1
    # device is visible), plus a FORCED single-device reference repeat so
    # the in-JSON speedup is attributable. Real-hardware target: >3x the
    # single-device 157.9k sigs/s flagship on the 8-device box.
    from tendermint_tpu.crypto.ed25519_jax import multidevice as MD

    md = MD.pool()
    if md is not None and len(md.eligible_lanes()) >= 2:
        # min-of-2 like-for-like: a single noisy reference pass (the relay
        # bandwidth swings 2-4x hour to hour) must not inflate the
        # multichip speedup ratio
        single_times = []
        with MD.disabled():
            for rep in range(2):
                pc = build_slice(20000 + rep * n_commits)
                t0 = time.perf_counter()
                sustained(pc)
                single_times.append(time.perf_counter() - t0)
                del pc
        single_rate = total_sigs / min(single_times)
        md_times = []
        for rep in range(repeats):
            pc = build_slice(30000 + rep * n_commits)
            t0 = time.perf_counter()
            sustained(pc)
            md_times.append(time.perf_counter() - t0)
            del pc
        md_rate = total_sigs / min(md_times)
        _emit("verify_commit_10k_multichip_sigs_per_sec", md_rate,
              "sigs/s", md_rate / host_rate,
              devices=len(md.eligible_lanes()),
              seg_chunks=md.seg_chunks,
              vs_single_device=round(md_rate / single_rate, 3),
              single_device_sigs_per_sec=round(single_rate, 1),
              target="3x single-device flagship (157.9k sigs/s r05) on "
                     "the 8-device box",
              per_repeat_sigs_per_sec=[round(total_sigs / t, 1)
                                       for t in md_times])
    else:
        # the crashed-config unit convention: a vanished pool must read
        # as ERRORED in bench_compare, never as silent absence
        n_lanes = 0 if md is None else len(md.eligible_lanes())
        _emit("verify_commit_10k_multichip_sigs_per_sec", 0.0, "error",
              0.0, error=f"multi-device pool unavailable "
                         f"({n_lanes} healthy lanes); see "
                         f"TMTPU_VERIFY_DEVICES / MULTICHIP regeneration "
                         f"in README")
    _emit("verify_commit_10k_sigs_per_sec", dev_rate, "sigs/s",
          dev_rate / host_rate,
          per_repeat_seconds=[round(t, 3) for t in repeat_times],
          per_repeat_sigs_per_sec=[round(total_sigs / t, 1)
                                   for t in repeat_times])


def bench_multichip_scale():
    """Config multichip: the devices x chunk scaling table through
    ``tools/device_profile.py scale`` — one fresh subprocess per device
    count, all three modes (sharded psum / raw threads x devices / the
    production MultiDeviceStream dispatcher). On CPU boxes the forced host
    mesh + shape-identical stub kernels measure the dispatch topology (the
    real-kernel rows come from the TPU box); MULTICHIP_r06.json is this
    table checked in."""
    dp = _tools_mod("device_profile")
    workload = dp.resolve_workload("auto")
    host_mesh = workload == "synthetic"
    devices = [1, 2, 4, 8]
    # 40960 sigs: at 8 lanes every lane still gets >=2 segments, so the
    # per-lane double-buffering the dispatcher is built on is measured
    res = dp.run_scale(devices, chunks=[CHUNK], sigs=40960,
                       workload=workload, host_mesh=host_mesh, runs=2,
                       threads=None)
    md_rows = sorted((r for r in res["table"] if r["mode"] == "multidev"),
                     key=lambda r: r["devices"])
    by_dev = {r["devices"]: r["sigs_per_sec"] for r in md_rows}
    mono = bool(by_dev) and all(
        by_dev[a] <= by_dev[b] * 1.05  # 5% noise allowance
        for a, b in zip(sorted(by_dev), sorted(by_dev)[1:]))
    _emit("verify_commit_10k_multichip_scaling", float(len(md_rows)),
          "rows", 0.0, workload=workload, host_mesh=host_mesh,
          monotone_through_max_devices=mono,
          multidev_sigs_per_sec_by_devices={str(d): by_dev[d]
                                            for d in sorted(by_dev)},
          table=res["table"], cell_errors=res.get("cell_errors"))


def bench_exec():
    """Config exec: the parallel-execution plane, measured (tools/
    execbench.py in-proc rig — no subprocess fleet, so it runs in slim
    containers).

    Gated row, from a seeded 4-validator in-proc fleet under an open-loop
    firehose of large-value disjoint-key txs (the payload where block
    execution dominates block time and speculation has maximum
    parallelism):

    * inproc_exec4_committed_txs_per_sec — committed txs/sec with
      execution.version=v1 (higher better). The A/B payload carries the
      matching SERIAL (v0) rate and the speedup: on a multi-core host the
      serial run visibly saturates first; on a 1-core host the executor
      caps its workers and the two rates converge (n_cpus says which
      world the row came from). Both fleets must land on the same app
      hash — the byte-parity invariant observed end-to-end.

    Informational row: inproc_exec4_phase_breakdown — the exec-plane
    phase decomposition of the parallel run's measured window (the
    per-block plane="exec" segments: validate=pack, tx execution=
    in-flight, commit+persist=fetch), same interval-union accounting as
    the device-plane profiles."""
    eb = _tools_mod("execbench")

    try:
        rep = eb.run_exec_ab(seed=1)
        par, ser = rep["parallel"], rep["serial"]
        _emit("inproc_exec4_committed_txs_per_sec", par["txs_per_sec"],
              "txs/s", rep["speedup"],
              serial_txs_per_sec=round(ser["txs_per_sec"], 3),
              speedup=round(rep["speedup"], 3), n_cpus=rep["n_cpus"],
              n_txs=rep["n_txs"], value_size=rep["value_size"],
              groups=par["parallel"]["groups"],
              conflicted=par["parallel"]["conflicted"],
              heights=par["heights"], app_hash=par["app_hash"])
        bd = par["exec_phase"]
        _emit("inproc_exec4_phase_breakdown",
              bd.get("device_share", 0.0), "ratio", 0.0,
              parallel=bd, serial=ser["exec_phase"])
    except Exception as e:
        _emit("inproc_exec4_committed_txs_per_sec", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")
        _emit("inproc_exec4_phase_breakdown", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")


def _mk_ed25519_commit_local(n_vals: int, chain_id: str, height: int = 100):
    """Ed25519 validator set + fully-signed commit built with the package's
    own keys (the aggsig A/B must run on hosts without OpenSSL bindings)."""
    import hashlib

    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.canonical import vote_sign_bytes

    privs = [crypto.Ed25519PrivKey.generate(
        hashlib.sha256(f"aggsig-ed-{chain_id}-{i}".encode()).digest())
        for i in range(n_vals)]
    vs = ValidatorSet([Validator(p.pub_key().address(), p.pub_key(), 10)
                       for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    sigs = []
    for i, v in enumerate(vs.validators):
        ts = 1_700_000_000_000_000_000 + i
        msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, height, 0,
                              bid, ts)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts,
                              by_addr[v.address].sign(msg)))
    return vs, Commit(height, 0, bid, sigs), bid


def _mk_bls_aggregated_commit(n_vals: int, chain_id: str, height: int = 100):
    """BLS validator set + one aggregated commit on a registered
    aggregate-commits chain: every validator signs the SAME zero-timestamp
    precommit payload; the signatures fold into one 48-byte G1 point."""
    import hashlib

    from tendermint_tpu import crypto
    from tendermint_tpu.crypto import bls12381 as bls
    from tendermint_tpu.crypto import schemes
    from tendermint_tpu.libs.bits import BitArray
    from tendermint_tpu.types import Validator, ValidatorSet
    from tendermint_tpu.types.basic import (
        BlockID,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import AggregatedCommit
    from tendermint_tpu.types.canonical import vote_sign_bytes
    from tendermint_tpu.types.params import SignatureParams

    schemes.register_chain(chain_id, SignatureParams("bls12381", True))
    privs = [crypto.Bls12381PrivKey.generate(
        hashlib.sha256(f"aggsig-bls-{chain_id}-{i}".encode()).digest())
        for i in range(n_vals)]
    vs = ValidatorSet([Validator(p.pub_key().address(), p.pub_key(), 10)
                       for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, height, 0,
                          bid, schemes.AGG_ZERO_TS_NS)
    agg = bls.aggregate([by_addr[v.address].sign(msg)
                         for v in vs.validators])
    signers = BitArray(n_vals)
    for i in range(n_vals):
        signers.set_index(i, True)
    commit = AggregatedCommit(height, 0, bid, [], signers=signers,
                              agg_sig=agg,
                              timestamp_ns=1_700_000_000_000_000_000)
    return vs, commit, bid


def bench_aggsig():
    """Config aggsig: commit verification A/B — ed25519 CommitSig lists
    through the batched verifier vs ONE BLS fast-aggregate-verify pairing —
    at 150 and 1000 validators, plus the informational commit-size row.
    Steady-state regime on both sides: the same commit re-verified (warm
    sign-bytes memo for ed25519, warm decompression/apk caches for BLS),
    which is what the consensus hot loop and light client replay pay per
    height once a validator set is live.  vs_baseline on the BLS rows is
    the A/B ratio against the ed25519-batched rate at the same scale.

    Two extensions ride along: the blocksync fast-sync replay A/B (a
    window of contiguous commits through verify_commit_light_batched per
    scheme — the stage-A dispatch path fast sync actually runs, closing
    the ROADMAP aggsig edge) and the BLS plane telemetry captured through
    a run-local DeviceMetrics (pairing wall time, aggregate-verify counts
    by mode, aggregated-commit wire-size observations)."""
    from tendermint_tpu.crypto import phases, schemes
    from tendermint_tpu.libs.metrics import DeviceMetrics, Registry
    from tendermint_tpu.types.validator_set import verify_commit_light_batched

    dm = DeviceMetrics(Registry("bench_aggsig"))
    prev_metrics = phases.metrics
    phases.set_device_metrics(dm)
    sizes = {}
    replay_window = 8
    try:
        for n_vals in (150, 1000):
            ed_chain = f"aggsig-ed-{n_vals}"
            vs_ed, commit_ed, bid_ed = _mk_ed25519_commit_local(
                n_vals, ed_chain)
            best_ed = _timed(lambda: vs_ed.verify_commit(
                ed_chain, bid_ed, 100, commit_ed), warm=2, runs=3)
            ed_rate = 1.0 / best_ed
            _emit(f"verify_commit_{n_vals}val_ed25519_batched_commits_per_sec",
                  ed_rate, "commits/s", 1.0, n_vals=n_vals)

            bls_chain = f"aggsig-bls-{n_vals}"
            vs_bls, commit_bls, bid_bls = _mk_bls_aggregated_commit(
                n_vals, bls_chain)
            best_bls = _timed(lambda: vs_bls.verify_commit(
                bls_chain, bid_bls, 100, commit_bls), warm=2, runs=3)
            bls_rate = 1.0 / best_bls
            _emit(f"verify_commit_{n_vals}val_bls_aggregated_commits_per_sec",
                  bls_rate, "commits/s", bls_rate / ed_rate, n_vals=n_vals)
            sizes[n_vals] = (len(commit_ed.encode()),
                             len(commit_bls.encode()))

        # -- blocksync fast-sync replay A/B (ROADMAP aggsig edge) ---------
        # the replay regime: a window of contiguous commits verified in
        # ONE verify_commit_light_batched call, exactly what the blocksync
        # reactor's stage-A dispatch pays per window. Ed25519 entries fold
        # into one device batch; aggregated commits verify inline, one
        # pairing each.
        def _replay(entries):
            errs = [e for e in verify_commit_light_batched(entries)
                    if e is not None]
            if errs:
                raise errs[0]

        bs_ed_chain = "aggsig-replay-ed-150"
        vs_e, commit_e, bid_e = _mk_ed25519_commit_local(150, bs_ed_chain)
        ed_entries = [(vs_e, bs_ed_chain, bid_e, 100, commit_e)
                      for _ in range(replay_window)]
        best = _timed(lambda: _replay(ed_entries), warm=2, runs=3)
        ed_replay_rate = replay_window / best
        _emit("blocksync_replay_150val_ed25519_commits_per_sec",
              ed_replay_rate, "commits/s", 1.0, window=replay_window)

        bs_bls_chain = "aggsig-replay-bls-150"
        vs_b, commit_b, bid_b = _mk_bls_aggregated_commit(150, bs_bls_chain)
        bls_entries = [(vs_b, bs_bls_chain, bid_b, 100, commit_b)
                       for _ in range(replay_window)]
        best = _timed(lambda: _replay(bls_entries), warm=2, runs=3)
        bls_replay_rate = replay_window / best
        _emit("blocksync_replay_150val_bls_commits_per_sec",
              bls_replay_rate, "commits/s", bls_replay_rate / ed_replay_rate,
              window=replay_window)
    finally:
        phases.set_device_metrics(prev_metrics)
        schemes.reset()
    # informational: the wire-size collapse (48 B sig + signer bitmap +
    # fixed header vs n_vals CommitSig entries) — never gated
    ed_b, agg_b = sizes[1000]
    _emit("aggregated_commit_1000val_bytes", float(agg_b), "bytes", 0.0,
          ed25519_commit_bytes=ed_b,
          agg_sig_bytes=48,
          compression_ratio=round(ed_b / agg_b, 1))
    # informational: the BLS plane telemetry the run just exercised,
    # read back through the run-local DeviceMetrics — pairing wall cost,
    # verify counts split by mode (full from the A/B, light from the
    # replay), and how many wire-size observations landed. Never gated.
    pair_calls = sum(dm.pairing_seconds._totals.values())
    pair_wall = sum(dm.pairing_seconds._sums.values())
    verify_by_mode = {"|".join(k): int(v) for k, v in
                      sorted(dm.aggregate_verify_total._values.items())}
    _emit("aggsig_pairing_telemetry", float(pair_calls), "calls", 0.0,
          pairing_wall_s_total=round(pair_wall, 6),
          pairing_wall_s_mean=round(pair_wall / pair_calls, 6)
          if pair_calls else 0.0,
          aggregate_verify_total=verify_by_mode,
          wire_size_observations=sum(
              dm.aggregated_commit_bytes._totals.values()))


def bench_soak():
    """Config soak: compressed in-proc game day (tools/soak.py). A 6-node
    fleet (4 validators + 2 fulls) under continuous open-loop signed load
    with corruption, churn and a crash-kill armed concurrently from one
    seed, judged against the default SLOSpec. Gated rows: SLO breach
    count (lower-better "breaches" unit), commit p99, and kill->caught-up
    recovery. Roughly a minute of chaos plus fleet spin-up/teardown; the
    full 8-node / 5-minute game day stays in tools/soak.py --ci."""
    import tempfile

    soak = _tools_mod("soak")
    try:
        out = os.path.join(tempfile.mkdtemp(prefix="bench_soak_"),
                           "soak_report.json")
        rep = soak.run_soak(n_nodes=6, seed=1, duration_s=60.0, out=out)
        sl = rep["slo"]
        _emit("inproc_soak_slo_breaches", float(len(sl["breaches"])),
              "breaches", 0.0, seed=rep["seed"], n_nodes=rep["n_nodes"],
              duration_s=rep["duration_s"],
              unattributed=sl["unattributed"],
              breach_planes=sorted({b["attribution"]["plane"]
                                    for b in sl["breaches"]}),
              schedule_fingerprint=rep["schedule_fingerprint"],
              breach_fingerprint=rep["breach_fingerprint"],
              heights=rep["heights"], event_errors=rep["event_errors"],
              report_path=out)
        obs = rep["observed"]
        if obs["commit_samples"]:
            _emit("inproc_soak_commit_p99_s", float(obs["commit_p99_s"]),
                  "s", 0.0, commit_samples=obs["commit_samples"],
                  rate_txs_per_s=rep["load"]["rate_txs_per_s"],
                  sent=rep["load"]["sent"])
        else:
            _emit("inproc_soak_commit_p99_s", 0.0, "error", 0.0,
                  error="no commit latency samples observed")
        recoveries = [k["kill_to_caughtup_s"] for k in rep["kills"]
                      if k.get("kill_to_caughtup_s") is not None]
        if recoveries:
            _emit("inproc_soak_kill_caughtup_s", float(max(recoveries)),
                  "s", 0.0, kills=len(rep["kills"]),
                  churn_caughtup_s=[round(j["caughtup_s"], 2)
                                    for j in rep["joins"]])
        else:
            # a kill that armed but never fired (or never rejoined) is a
            # regression the gate must see, not a silently missing row
            _emit("inproc_soak_kill_caughtup_s", 0.0, "error", 0.0,
                  error="no completed kill->rejoin cycle",
                  kills=rep["kills"], event_errors=rep["event_errors"])
    except Exception as e:
        for m in ("inproc_soak_slo_breaches", "inproc_soak_commit_p99_s",
                  "inproc_soak_kill_caughtup_s"):
            _emit(m, 0.0, "error", 0.0, error=f"{type(e).__name__}: {e}")


def bench_wan():
    """Config wan: the degraded-network plane (tools/quorum_loss.py). Two
    gated rows: 4-validator commit throughput under the seeded ``wan``
    link profile (80-160ms asymmetric latency + jitter on every link;
    higher-better "commits/min"), and worst-case quorum-loss recovery —
    >1/3 of voting power isolated until the fleet halts with
    ``halt_reason="quorum_lost"``, then healed; the row is the worst
    heal->next-commit time across windows (lower-better "s"). Both runs
    also assert the safety half (no conflicting commits, no double-sign
    evidence, hash-identical history), so a regression that trades
    safety for speed errors the row instead of improving it."""
    ql = _tools_mod("quorum_loss")
    try:
        rep = ql.run_wan(seed=1, blocks=12)
        _emit("inproc_wan4_commits_per_min", float(rep["commits_per_min"]),
              "commits/min", 0.0, seed=rep["seed"], blocks=rep["blocks"],
              applied_links=rep["applied_links"],
              elapsed_s=rep["elapsed_s"])
    except Exception as e:
        _emit("inproc_wan4_commits_per_min", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")
    try:
        rep = ql.run_quorum_loss(seed=1, windows=2)
        _emit("inproc_quorumloss_recover_s", float(rep["recover_max_s"]),
              "s", 0.0, seed=rep["seed"], windows=rep["windows"],
              recover_s=[w["recover_s"] for w in rep["windows_run"]],
              halt_heights=[w["halt_height"] for w in rep["windows_run"]],
              hash_identical=rep["hash_identical"],
              equivocations=rep["equivocations"],
              outcome_fingerprint=rep["outcome_fingerprint"])
    except Exception as e:
        _emit("inproc_quorumloss_recover_s", 0.0, "error", 0.0,
              error=f"{type(e).__name__}: {e}")


def _mk_light_serve_chain(n_vals: int, n_heights: int, chain_id: str,
                          scheme: str = "ed25519"):
    """Signed LightBlock chain for the serving-plane A/B: real headers
    (hash-linked, valset hashes bound) with fully-signed commits — the
    CommitSig list per height for ed25519, ONE aggregate per height on a
    registered BLS chain."""
    import hashlib

    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig, Consensus, Header
    from tendermint_tpu.types.canonical import vote_sign_bytes
    from tendermint_tpu.types.light_block import LightBlock, SignedHeader

    t0_ns = 1_700_000_000_000_000_000
    if scheme == "bls12381":
        from tendermint_tpu.crypto import bls12381 as bls
        from tendermint_tpu.crypto import schemes
        from tendermint_tpu.libs.bits import BitArray
        from tendermint_tpu.types.block import AggregatedCommit
        from tendermint_tpu.types.params import SignatureParams

        schemes.register_chain(chain_id, SignatureParams("bls12381", True))
        privs = [crypto.Bls12381PrivKey.generate(
            hashlib.sha256(f"lsrv-{chain_id}-{i}".encode()).digest())
            for i in range(n_vals)]
    else:
        privs = [crypto.Ed25519PrivKey.generate(
            hashlib.sha256(f"lsrv-{chain_id}-{i}".encode()).digest())
            for i in range(n_vals)]
    vs = ValidatorSet([Validator(p.pub_key().address(), p.pub_key(), 10)
                       for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    blocks = {}
    last_bid = BlockID(b"", PartSetHeader())
    for h in range(1, n_heights + 1):
        header = Header(
            version=Consensus(), chain_id=chain_id, height=h,
            time_ns=t0_ns + h * 1_000_000_000, last_block_id=last_bid,
            last_commit_hash=b"\x01" * 32, data_hash=b"\x02" * 32,
            validators_hash=vs.hash(), next_validators_hash=vs.hash(),
            consensus_hash=b"\x03" * 32, app_hash=b"\x04" * 32,
            last_results_hash=b"\x05" * 32, evidence_hash=b"\x06" * 32,
            proposer_address=vs.validators[0].address)
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x07" * 32))
        if scheme == "bls12381":
            from tendermint_tpu.crypto import schemes

            msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, h, 0,
                                  bid, schemes.AGG_ZERO_TS_NS)
            agg = bls.aggregate([by_addr[v.address].sign(msg)
                                 for v in vs.validators])
            signers = BitArray(n_vals)
            for i in range(n_vals):
                signers.set_index(i, True)
            commit = AggregatedCommit(h, 0, bid, [], signers=signers,
                                      agg_sig=agg,
                                      timestamp_ns=header.time_ns)
        else:
            sigs = []
            for i, v in enumerate(vs.validators):
                ts = header.time_ns + i
                msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT,
                                      h, 0, bid, ts)
                sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts,
                                      by_addr[v.address].sign(msg)))
            commit = Commit(h, 0, bid, sigs)
        blocks[h] = LightBlock(SignedHeader(header, commit), vs)
        last_bid = bid
    return blocks


def _lightserve_requests(blocks, spans, per_span: int, now_ns: int):
    """The fleet's ask: ``per_span`` clients per (trusted, target) span —
    the steady-state where thousands of clients bisect the same heights."""
    from tendermint_tpu.light.serve import VerifyRequest

    reqs = []
    for i in range(per_span):
        for t, h in spans:
            reqs.append(VerifyRequest(
                blocks[t].signed_header, blocks[t].validator_set,
                blocks[h].signed_header, blocks[h].validator_set,
                3600.0, now_ns, 10.0, (1, 3), cache_key=(t, h)))
    return reqs


def _lightserve_run_coalesced(reqs, flush_max: int = 64,
                              deadline_s: float = 0.002):
    """One fleet burst through a FRESH coalescer; returns (wall, per-client
    sojourn latencies, coalescer stats)."""
    import asyncio

    from tendermint_tpu.light.serve import VerifyCoalescer

    lat = []

    async def run():
        co = VerifyCoalescer(flush_deadline_s=deadline_s,
                             flush_max=flush_max)
        try:
            async def one(r):
                t0 = time.perf_counter()
                res = await co.submit(r)
                lat.append(time.perf_counter() - t0)
                return res

            t0 = time.perf_counter()
            results = await asyncio.gather(*[one(r) for r in reqs])
            wall = time.perf_counter() - t0
            bad = [r for r in results if r is not None]
            assert not bad, f"coalesced serving rejected honest spans: {bad[:2]}"
            return wall, dict(co.stats)
        finally:
            co.stop()

    wall, stats = asyncio.run(run())
    return wall, lat, stats


def _lightserve_run_scalar(reqs):
    """The pre-coalescer serving plane: one scalar verifier.verify per
    request, FIFO. Latencies are sojourn times for a burst arriving at t0 —
    what a concurrent client actually waits on a one-at-a-time server."""
    from tendermint_tpu.light import verifier

    lat = []
    t0 = time.perf_counter()
    for r in reqs:
        verifier.verify(r.trusted_sh, r.trusted_vals, r.untrusted_sh,
                        r.untrusted_vals, r.trusting_period_s, r.now_ns,
                        r.max_clock_drift_s, r.trust_level)
        lat.append(time.perf_counter() - t0)
    return time.perf_counter() - t0, lat


def _p99(lat):
    s = sorted(lat)
    return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999))]


def bench_lightserve():
    """Config lightserve: the light-client serving plane A/B. A 96-client
    fleet trusting-verifies a handful of spans over a 16-validator chain:
    scalar = one verifier.verify per request FIFO (the pre-plane serving
    path); coalesced = the same requests through VerifyCoalescer (ONE
    batched precompute + scalar-spec replay, dedup + verdict cache).
    Gated rows: fleet headers/s (higher-better; vs_baseline is the A/B
    ratio over scalar) and p99 client sojourn (lower-better). The BLS
    aggregated plane rides along at 8 validators — a flush there is a
    handful of pairings."""
    from tendermint_tpu.crypto import schemes

    t0_ns = 1_700_000_000_000_000_000
    now_ns = t0_ns + 100 * 1_000_000_000
    try:
        blocks = _mk_light_serve_chain(16, 12, "lightserve-bench-ed")
        spans = [(1, 12), (2, 12), (1, 8), (3, 10), (2, 9), (4, 11)]
        reqs = _lightserve_requests(blocks, spans, 16, now_ns)  # 96 clients

        _lightserve_run_scalar(reqs)  # warm (sign-bytes memos, jit)
        _lightserve_run_coalesced(reqs)
        sc_wall = sc_lat = None
        for _ in range(3):
            wall, lat = _lightserve_run_scalar(reqs)
            if sc_wall is None or wall < sc_wall:
                sc_wall, sc_lat = wall, lat
        co_wall = co_lat = stats = None
        for _ in range(3):
            wall, lat, st = _lightserve_run_coalesced(reqs)
            if co_wall is None or wall < co_wall:
                co_wall, co_lat, stats = wall, lat, st
        scalar_rate = len(reqs) / sc_wall
        co_rate = len(reqs) / co_wall
        _emit("lightserve_clients_headers_per_sec", co_rate, "headers/s",
              co_rate / scalar_rate, clients=len(reqs),
              spans=len(spans), scalar_headers_per_sec=round(scalar_rate, 1),
              flushes=stats["flushes"], largest_flush=stats["largest_flush"],
              verified_requests=stats["verified_requests"],
              coalesced_dupes=stats["coalesced_dupes"],
              verdict_cache_hits=stats["verdict_cache_hits"],
              batched_sigs=stats["batched_sigs"])
        _emit("lightserve_p99_s", _p99(co_lat), "s",
              _p99(co_lat) / _p99(sc_lat), clients=len(reqs),
              scalar_p99_s=round(_p99(sc_lat), 6),
              scalar_p50_s=round(sorted(sc_lat)[len(sc_lat) // 2], 6),
              coalesced_p50_s=round(sorted(co_lat)[len(co_lat) // 2], 6))

        # the BLS aggregated plane: same fleet discipline, pairing regime
        bls_blocks = _mk_light_serve_chain(8, 8, "lightserve-bench-bls",
                                           scheme="bls12381")
        bls_spans = [(1, 8), (2, 8), (1, 5), (3, 7)]
        bls_reqs = _lightserve_requests(bls_blocks, bls_spans, 8, now_ns)
        _lightserve_run_scalar(bls_reqs)
        _lightserve_run_coalesced(bls_reqs)
        bls_sc_wall, _ = _lightserve_run_scalar(bls_reqs)
        bls_co_wall, _, bls_stats = _lightserve_run_coalesced(bls_reqs)
        bls_sc_rate = len(bls_reqs) / bls_sc_wall
        bls_co_rate = len(bls_reqs) / bls_co_wall
        _emit("lightserve_bls_clients_headers_per_sec", bls_co_rate,
              "headers/s", bls_co_rate / bls_sc_rate, clients=len(bls_reqs),
              scalar_headers_per_sec=round(bls_sc_rate, 1),
              verified_requests=bls_stats["verified_requests"],
              batched_sigs=bls_stats["batched_sigs"])
    except Exception as e:
        for m in ("lightserve_clients_headers_per_sec", "lightserve_p99_s",
                  "lightserve_bls_clients_headers_per_sec"):
            _emit(m, 0.0, "error", 0.0, error=f"{type(e).__name__}: {e}")
    finally:
        schemes.reset()


CONFIGS = {
    "1": bench_stream,
    "2": bench_verify_commit_150,
    "3": bench_light_chain_1000,
    "4": bench_localnet,
    "5": bench_fast_sync_replay,
    "ingest": bench_ingest,
    "multichip": bench_multichip_scale,
    "churn": bench_churn,
    "crash": bench_crash,
    "exec": bench_exec,
    "aggsig": bench_aggsig,
    "lightserve": bench_lightserve,
    "soak": bench_soak,
    "wan": bench_wan,
    "10k": bench_verify_commit_10k,
}


def _emit_trace(path: str) -> None:
    """Write the run's span trace as Chrome trace-event JSON (loadable at
    https://ui.perfetto.dev) and emit a per-span stage-histogram summary
    line into the BENCH_*.json payload."""
    import sys

    from tendermint_tpu.libs.trace import tracer

    tracer.write(path)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tools"))
    try:
        from trace_summary import summarize

        spans = summarize(tracer.events())
    finally:
        sys.path.pop(0)
    _emit("trace_summary", float(len(tracer.events())), "events", 0.0,
          trace_path=path, spans=spans)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="all",
                    choices=list(CONFIGS) + ["all"],
                    help="BASELINE.json config; default runs every config, "
                         "flagship (10k) last")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="enable the span tracer (libs/trace.py) for the "
                         "whole run and write Chrome trace-event JSON here; "
                         "also emits a per-span summary line")
    args = ap.parse_args()
    _enable_compile_cache()
    from tendermint_tpu.libs.trace import tracer as _tracer

    if args.trace_out:
        _tracer.enable()
    try:
        if args.config == "all":
            # flagship last: the driver records the final line. The remote
            # relay occasionally drops a compile mid-flight — retry each
            # config once before reporting it failed.
            for key in ("2", "3", "4", "ingest", "churn", "crash", "exec",
                        "aggsig", "lightserve", "soak", "wan", "5", "1",
                        "multichip", "10k"):
                for attempt in (1, 2):
                    try:
                        with _tracer.span(f"config_{key}"):
                            CONFIGS[key]()
                        break
                    except Exception as e:
                        if attempt == 2:
                            _emit(f"config_{key}_failed", 0.0, "error", 0.0,
                                  error=f"{type(e).__name__}: {e}")
                        else:
                            time.sleep(5.0)
        else:
            with _tracer.span(f"config_{args.config}"):
                CONFIGS[args.config]()
    finally:
        # a failed run is exactly when the trace matters: flush the ring
        # to disk before any exception propagates
        if args.trace_out:
            _emit_trace(args.trace_out)
