"""Benchmark: Ed25519 commit-verification throughput, TPU stream vs host scalar.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.json config #1: the batched verifier on realistic vote sign-bytes
(identical in shape to types.Commit.vote_sign_bytes output), measured as
*sustained* throughput — a stream of 1024-signature chunks verified by one
``lax.scan`` inside a single device execution. That is the shape of the real
hot paths (fast-sync replay, 10k-validator commits, vote-stream batches):
dispatching one jitted call has a large fixed cost on remote-attached TPUs
(~100 ms through a relay), so per-call latency at batch 1024 measures the
link, not the machine; the stream amortizes it exactly the way the
consensus/blocksync callers do.

Baseline = the host scalar loop (OpenSSL-backed PubKey.verify_signature, the
stand-in for the reference's Go x/crypto ed25519.Verify hot call at
crypto/ed25519/ed25519.go:148-155), measured on a 2048-signature subset.

Timing includes host-side packing (prepare_batch) — the device path is
charged end-to-end, same as the baseline loop.
"""

import json
import time

import numpy as np

N_STREAM = 32768
CHUNK = 1024
N_BASE = 2048


def build_batch(n: int):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    rng = np.random.default_rng(7)
    pks, msgs, sigs, pubs = [], [], [], []
    for i in range(n):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub_bytes = priv.public_key().public_bytes_raw()
        # realistic vote sign-bytes (unique timestamp per validator)
        msg = vote_sign_bytes("bench-chain", SignedMsgType.PRECOMMIT, 100, 0,
                              bid, 1_700_000_000_000_000_000 + i)
        pks.append(pub_bytes)
        msgs.append(msg)
        sigs.append(priv.sign(msg))
        pubs.append(crypto.Ed25519PubKey(pub_bytes))
    return pks, msgs, sigs, pubs


def main():
    pks, msgs, sigs, pubs = build_batch(N_STREAM)

    from tendermint_tpu.crypto.ed25519_jax import batch_verify_stream

    # warmup: compile the stream kernel at the measured shape (cached across
    # runs by the jax persistent cache when available)
    out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
    assert np.asarray(out).all(), "warmup stream rejected valid sigs"

    # device path: best of 3 timed runs, end-to-end incl. host packing
    device_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        device_times.append(time.perf_counter() - t0)
    assert np.asarray(out).all()
    device_sigs_per_sec = N_STREAM / min(device_times)

    # host scalar baseline (the reference's one-verify-per-signature loop)
    t0 = time.perf_counter()
    ok = all(pub.verify_signature(m, s)
             for pub, m, s in zip(pubs[:N_BASE], msgs[:N_BASE], sigs[:N_BASE]))
    host_elapsed = time.perf_counter() - t0
    assert ok
    host_sigs_per_sec = N_BASE / host_elapsed

    print(json.dumps({
        "metric": "verify_commit_sigs_per_sec_stream1024",
        "value": round(device_sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(device_sigs_per_sec / host_sigs_per_sec, 3),
    }))


if __name__ == "__main__":
    main()
