"""Benchmark: Ed25519 commit-verification throughput, TPU stream vs host scalar.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.json config #1: the batched verifier on realistic vote sign-bytes
(identical in shape to types.Commit.vote_sign_bytes output), measured as
*sustained* throughput — a stream of 1024-signature chunks verified by one
``lax.scan`` inside a single device execution. That is the shape of the real
hot paths (fast-sync replay, 10k-validator commits, vote-stream batches):
dispatching one jitted call has a large fixed cost on remote-attached TPUs
(~100 ms through a relay), so per-call latency at batch 1024 measures the
link, not the machine; the stream amortizes it exactly the way the
consensus/blocksync callers do.

Baseline = the host scalar loop (OpenSSL-backed PubKey.verify_signature, the
stand-in for the reference's Go x/crypto ed25519.Verify hot call at
crypto/ed25519/ed25519.go:148-155), measured on a 2048-signature subset.

Timing includes host-side packing (prepare_batch) — the device path is
charged end-to-end, same as the baseline loop.
"""

import argparse
import json
import os
import time

import numpy as np

N_STREAM = 32768
CHUNK = 1024
N_BASE = 2048


def build_batch(n: int):
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    rng = np.random.default_rng(7)
    pks, msgs, sigs, pubs = [], [], [], []
    for i in range(n):
        priv = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub_bytes = priv.public_key().public_bytes_raw()
        # realistic vote sign-bytes (unique timestamp per validator)
        msg = vote_sign_bytes("bench-chain", SignedMsgType.PRECOMMIT, 100, 0,
                              bid, 1_700_000_000_000_000_000 + i)
        pks.append(pub_bytes)
        msgs.append(msg)
        sigs.append(priv.sign(msg))
        pubs.append(crypto.Ed25519PubKey(pub_bytes))
    return pks, msgs, sigs, pubs


def main():
    pks, msgs, sigs, pubs = build_batch(N_STREAM)

    from tendermint_tpu.crypto.ed25519_jax import batch_verify_stream

    # warmup: compile the stream kernel at the measured shape (cached across
    # runs by the jax persistent cache when available)
    out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
    assert np.asarray(out).all(), "warmup stream rejected valid sigs"

    # device path: best of 3 timed runs, end-to-end incl. host packing
    device_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = batch_verify_stream(pks, msgs, sigs, chunk=CHUNK)
        device_times.append(time.perf_counter() - t0)
    assert np.asarray(out).all()
    device_sigs_per_sec = N_STREAM / min(device_times)

    # host scalar baseline (the reference's one-verify-per-signature loop)
    t0 = time.perf_counter()
    ok = all(pub.verify_signature(m, s)
             for pub, m, s in zip(pubs[:N_BASE], msgs[:N_BASE], sigs[:N_BASE]))
    host_elapsed = time.perf_counter() - t0
    assert ok
    host_sigs_per_sec = N_BASE / host_elapsed

    print(json.dumps({
        "metric": "verify_commit_sigs_per_sec_stream1024",
        "value": round(device_sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(device_sigs_per_sec / host_sigs_per_sec, 3),
    }))


# --- BASELINE configs #2/#3/#5 (VerifyCommit paths) -------------------------

def _mk_val_set(n_vals: int, seed: int = 7):
    """A validator set + its signing keys (OpenSSL), reusable across heights."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    from tendermint_tpu import crypto
    from tendermint_tpu.types import Validator, ValidatorSet

    rng = np.random.default_rng(seed)
    keys = {}
    vals = []
    for _ in range(n_vals):
        sk = Ed25519PrivateKey.from_private_bytes(
            bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
        pub = crypto.Ed25519PubKey(sk.public_key().public_bytes_raw())
        keys[pub.address()] = sk
        vals.append(Validator(pub.address(), pub, 10))
    return ValidatorSet(vals), keys


def _sign_commit(vs, keys, height: int, chain_id: str):
    """A canonical commit for `height` signed by every validator, in
    validator-set order."""
    from tendermint_tpu.types.basic import (
        BlockID,
        BlockIDFlag,
        PartSetHeader,
        SignedMsgType,
    )
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.canonical import vote_sign_bytes

    bid = BlockID(hash(("bench", height)).to_bytes(8, "big", signed=True) * 4,
                  PartSetHeader(1, b"\x02" * 32))
    sigs = []
    for i, v in enumerate(vs.validators):
        ts = 1_700_000_000_000_000_000 + height * 1_000_000 + i
        msg = vote_sign_bytes(chain_id, SignedMsgType.PRECOMMIT, height, 0,
                              bid, ts)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts,
                              keys[v.address].sign(msg)))
    return Commit(height, 0, bid, sigs), bid


def _timed(fn, warm: int = 1, runs: int = 3) -> float:
    for _ in range(warm):
        fn()
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_verify_commit_150():
    """Config #2: ValidatorSet.VerifyCommit over a 150-validator commit
    (reference types/validator_set.go:667)."""
    vs, keys = _mk_val_set(150)
    commit, bid = _sign_commit(vs, keys, 100, "bench-150")
    dev = _timed(lambda: vs.verify_commit("bench-150", bid, 100, commit))
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(lambda: vs.verify_commit("bench-150", bid, 100, commit))
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    print(json.dumps({
        "metric": "verify_commit_150_vals_sigs_per_sec",
        "value": round(150 / dev, 1), "unit": "sigs/s",
        "vs_baseline": round(host / dev, 3),
    }))


def bench_light_chain_1000():
    """Config #3: light-client VerifyCommitLight+Trusting over a
    1000-validator header chain (reference validator_set.go:722,775,
    light/verifier.go:32). Device path = verify_chain_batched: every
    signature across the range rides ONE device call."""
    from tendermint_tpu.crypto.batch import BatchVerifier, precomputed_verdicts

    n_vals, n_headers = 1000, 8
    vs, keys = _mk_val_set(n_vals)
    commits = [_sign_commit(vs, keys, h, "bench-light")[0]
               for h in range(2, n_headers + 2)]
    trust = (1, 3)

    def verify_chain_device():
        # the chain-batched pattern: batch ALL sigs, then replay semantics
        bv = BatchVerifier(backend="jax")
        pre_keys = []
        for c in commits:
            for idx, cs in enumerate(c.signatures):
                if cs.for_block():
                    pk = vs.validators[idx].pub_key
                    sb = c.vote_sign_bytes("bench-light", idx)
                    bv.add(pk, sb, cs.signature)
                    pre_keys.append((pk.bytes(), sb, cs.signature))
        _, verdicts = bv.verify()
        token = precomputed_verdicts.set(
            {k: bool(v) for k, v in zip(pre_keys, verdicts)})
        try:
            for c in commits:
                vs.verify_commit_light_trusting("bench-light", c, trust)
                vs.verify_commit_light("bench-light", c.block_id, c.height, c)
        finally:
            precomputed_verdicts.reset(token)

    def verify_chain():
        for c in commits:
            vs.verify_commit_light_trusting("bench-light", c, trust)
            vs.verify_commit_light("bench-light", c.block_id, c.height, c)

    dev = _timed(verify_chain_device)
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(verify_chain, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    # sigs verified per pass: trusting tallies ~all, light stops at 2/3
    sigs = n_headers * (n_vals + 2 * n_vals // 3 + 1)
    print(json.dumps({
        "metric": "light_chain_1000_vals_sigs_per_sec",
        "value": round(sigs / dev, 1), "unit": "sigs/s",
        "vs_baseline": round(host / dev, 3),
    }))


def bench_fast_sync_replay():
    """Config #5 (scaled): the block-sync engine's windowed batched commit
    verification over a 1000-validator chain (reference
    blockchain/v0/reactor.go:255; our blockchain/reactor.py:186). Measures
    the verification plane, which is the reference's fast-sync bottleneck."""
    from tendermint_tpu.types.validator_set import verify_commit_light_batched

    n_vals, n_blocks, window = 1000, 64, 16
    vs, keys = _mk_val_set(n_vals)
    entries = []
    for h in range(1, n_blocks + 1):
        commit, bid = _sign_commit(vs, keys, h, "bench-sync")
        entries.append((vs, "bench-sync", bid, h, commit))

    def replay():
        for i in range(0, n_blocks, window):
            errs = verify_commit_light_batched(entries[i:i + window])
            assert all(e is None for e in errs), errs

    dev = _timed(replay)
    os.environ["TMTPU_BATCH_BACKEND"] = "host"
    try:
        host = _timed(replay, warm=0, runs=1)
    finally:
        del os.environ["TMTPU_BATCH_BACKEND"]
    print(json.dumps({
        "metric": "fast_sync_1000_vals_blocks_per_sec",
        "value": round(n_blocks / dev, 2), "unit": "blocks/s",
        "vs_baseline": round(host / dev, 3),
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=1, choices=(1, 2, 3, 5),
                    help="BASELINE.json config: 1=batch stream (default, the "
                         "driver metric), 2=VerifyCommit@150, 3=light chain "
                         "@1000, 5=fast-sync replay @1000")
    args = ap.parse_args()
    {1: main, 2: bench_verify_commit_150, 3: bench_light_chain_1000,
     5: bench_fast_sync_replay}[args.config]()
